"""Pass orchestration: run passes, apply per-line suppressions and the
committed baseline, enforce that both are *exercised*.

Suppression policy (the framework's own rules, reported under framework
pass ids):

- ``unused-suppression`` — a ``# graftlint: disable=<pass>`` comment on
  a line the named pass no longer flags.  Suppressions are load-bearing
  documentation; a stale one claims a hazard that is not there.  Only
  enforced when the full default pass set runs (a ``--passes`` subset
  cannot tell "unused" from "not checked this run").
- ``stale-baseline`` — a baseline entry no finding matched.  Same
  argument, for the grandfather file.

Baseline format (``scripts/graftlint/baseline.txt``)::

    <pass-id> <path>::<symbol>   # one-line justification

Symbols (the enclosing function) key the match instead of line numbers,
so routine edits above a grandfathered site don't churn the file.
"""

from __future__ import annotations

import os

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .core import Finding, Project
from .passes import ALL_PASSES

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def all_passes() -> list:
    return [cls() for cls in ALL_PASSES]


@dataclass
class BaselineEntry:
    fingerprint: str
    justification: str
    line: int
    hits: int = 0


def load_baseline(path: str) -> List[BaselineEntry]:
    if not os.path.isfile(path):
        return []
    entries = []
    with open(path) as f:
        for i, raw in enumerate(f, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, why = line.partition("#")
            parts = body.split()
            if len(parts) != 2 or "::" not in parts[1]:
                entries.append(BaselineEntry(
                    fingerprint=f"<malformed:{line}>",
                    justification="", line=i))
                continue
            entries.append(BaselineEntry(
                fingerprint=f"{parts[0]} {parts[1]}",
                justification=why.strip(), line=i))
    return entries


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.pass_id] = out.get(f.pass_id, 0) + 1
        return out

    def as_dict(self) -> dict:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "counts": self.counts(),
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "files_scanned": self.files_scanned,
        }

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        counts = self.counts()
        if counts:
            per = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
            lines.append(f"graftlint: {len(self.findings)} finding(s) "
                         f"({per}); {len(self.baselined)} baselined, "
                         f"{len(self.suppressed)} suppressed")
        else:
            lines.append(
                f"graftlint clean ({self.files_scanned} file(s); "
                f"{len(self.baselined)} baselined, "
                f"{len(self.suppressed)} suppressed finding(s))")
        return "\n".join(lines)


def run(repo: str = REPO, passes: Optional[Sequence] = None,
        paths: Optional[Sequence[str]] = None,
        baseline_path: str = BASELINE,
        enforce_suppressions: Optional[bool] = None) -> Report:
    """Run ``passes`` (default: all) over ``repo``; apply suppressions
    and the baseline.  ``paths`` narrows AST passes to explicit files or
    directories (whole-repo passes like bench-schema skip themselves
    when a narrowing is active — see ``BenchSchemaPass.run``)."""
    project = Project(repo=repo)
    chosen = list(passes) if passes is not None else all_passes()
    if enforce_suppressions is None:
        enforce_suppressions = (passes is None and paths is None)
    for p in paths or ():
        # a typo'd CI path must fail loudly, never pass by checking
        # zero files (the legacy checkers raised here too)
        if not (os.path.exists(p)
                or os.path.exists(os.path.join(repo, p))):
            raise FileNotFoundError(f"graftlint: no such path: {p}")

    raw: List[Finding] = []
    for p in chosen:
        raw += p.run(project, paths=paths)
    no_baseline = {p.id for p in chosen if p.baseline_exempt}

    report = Report(files_scanned=len(project.scanned))
    by_rel = {m.rel: m for m in project._cache.values()}
    used: Dict[str, set] = {}        # module path -> {(line, pass_id)}
    for f in raw:
        mod = by_rel.get(f.path)
        disabled = mod.suppressions.get(f.line, set()) if mod else set()
        if f.pass_id in disabled or "all" in disabled:
            used.setdefault(mod.path, set()).add(
                (f.line, f.pass_id if f.pass_id in disabled else "all"))
            report.suppressed.append(f)
        else:
            report.findings.append(f)

    entries = load_baseline(baseline_path)
    by_fp = {e.fingerprint: e for e in entries}
    kept = []
    for f in report.findings:
        entry = None if f.pass_id in no_baseline \
            else by_fp.get(f.fingerprint)
        if entry is not None:
            entry.hits += 1
            report.baselined.append(f)
        else:
            kept.append(f)
    report.findings = kept

    if enforce_suppressions:
        base_rel = os.path.relpath(baseline_path, repo)
        for e in entries:
            if not e.hits:
                report.findings.append(Finding(
                    pass_id="stale-baseline", path=base_rel, line=e.line,
                    message=(f"baseline entry {e.fingerprint!r} matched no "
                             "finding — the grandfathered hazard is gone"),
                    hint="delete the entry (or fix the fingerprint)"))
        for mod_path in sorted(project.scanned):
            mod = project._cache[mod_path]
            for line, ids in sorted(mod.suppressions.items()):
                for pass_id in sorted(ids):
                    if (line, pass_id) in used.get(mod_path, set()):
                        continue
                    report.findings.append(Finding(
                        pass_id="unused-suppression", path=mod.rel,
                        line=line,
                        message=(f"'# graftlint: disable={pass_id}' "
                                 "suppresses nothing on this line"),
                        hint="remove the comment (the hazard it claims "
                             "is not flagged here)"))
    report.findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return report
