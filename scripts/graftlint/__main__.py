"""CLI: ``python -m scripts.graftlint [paths...] [--json FILE|-]``.

Exit 0 = clean (baselined/suppressed findings don't fail the run),
1 = findings.  ``--json`` additionally emits the machine-readable
report (finding list + per-pass counts) so CI tooling can diff finding
counts across PRs.
"""

from __future__ import annotations

import argparse
import json
import sys

from .runner import all_passes, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m scripts.graftlint",
        description="unified static-analysis gate (see scripts/graftlint)")
    ap.add_argument("paths", nargs="*",
                    help="restrict AST passes to these files/dirs "
                         "(default: each pass's own roots)")
    ap.add_argument("--json", metavar="FILE",
                    help="write the JSON report to FILE ('-' = stdout)")
    ap.add_argument("--passes", metavar="ID[,ID...]",
                    help="comma-separated pass ids to run (default: all; "
                         "disables unused-suppression enforcement)")
    ap.add_argument("--list-passes", action="store_true",
                    help="print the pass catalog and exit")
    args = ap.parse_args(argv)

    catalog = all_passes()
    if args.list_passes:
        for p in catalog:
            print(f"{p.id:24s} {p.describes}")
        return 0

    chosen = None
    if args.passes:
        wanted = {s.strip() for s in args.passes.split(",") if s.strip()}
        known = {p.id for p in catalog}
        unknown = wanted - known
        if unknown:
            print(f"unknown pass id(s): {', '.join(sorted(unknown))} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
        chosen = [p for p in catalog if p.id in wanted]

    try:
        report = run(passes=chosen, paths=args.paths or None)
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    # with ``--json -`` stdout IS the machine-readable report; the
    # human-readable rendering moves to stderr so the stream parses
    print(report.render(),
          file=sys.stderr if args.json == "-" else sys.stdout)
    if args.json:
        payload = json.dumps(report.as_dict(), indent=2, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            import os

            tmp = args.json + ".tmp"
            with open(tmp, "w") as f:
                f.write(payload + "\n")
            os.replace(tmp, args.json)
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
