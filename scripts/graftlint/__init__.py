"""graftlint — the repo's unified static-analysis framework (ISSUE 8).

PRs 1-7 each found a *convention* violation by hand: the
``flush_lock``-across-``put`` deadlock (PR 1), the top_k-inside-manual-
region XLA abort (PR 3), the zombie-reader race (PR 7).  This package
turns those conventions into enforced passes over ONE shared
infrastructure — qualified-name resolution through import aliases and
local rebinding, follow-functions-passed-by-reference, per-line
``# graftlint: disable=<pass>`` suppressions with unused-suppression
enforcement, and a committed baseline for grandfathered findings
(``scripts/graftlint/baseline.txt``).

Run everything::

    python -m scripts.graftlint            # all passes, exit 0 = clean
    python -m scripts.graftlint --json -   # machine-readable findings

Passes (see ``scripts/graftlint/passes/``):

- ``host-sync``               no host synchronization inside step/scan
                              bodies (absorbed from check_no_host_sync)
- ``atomic-writes``           durable-layer writes are tmp -> os.replace
                              (absorbed from check_atomic_writes)
- ``donation-safety``         a value passed at a ``donate_argnums``
                              position is never read again
- ``lock-discipline``         no Lock held across a blocking call
- ``collective-consistency``  collectives inside manual regions stay
                              well-formed across branches
- ``bench-schema``            bench.py <-> BENCH_SCHEMA.md drift (non-AST,
                              delegates to check_bench_schema)

Wired into tier-1 via ``tests/test_graftlint.py``.
"""

from .core import Finding, ModuleInfo, Project, iter_py_files  # noqa: F401
from .runner import Report, all_passes, run  # noqa: F401

__all__ = ["Finding", "ModuleInfo", "Project", "Report", "all_passes",
           "iter_py_files", "run"]
