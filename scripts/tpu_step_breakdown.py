"""Attribute the CURRENT (r4 two-kernel) mixed-ELL step cost on real TPU.

One run, shared chip conditions: in-situ drop-one legs of the planned
step inside the same fused epoch loop the bench times, plus standalone
per-call timings of each Mosaic kernel at bench shape.  Two-point fits
over epoch counts cancel fixed dispatch.

Run: timeout 1800 python -u scripts/tpu_step_breakdown.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import (
    SGDConfig,
    _ext_len,
    _extended_r,
    _mixed_update_ell,
)
from flink_ml_tpu.ops.ell_scatter import (
    ell_layout_device,
    ell_margin_fused,
    ell_scatter_apply_fused,
)

D = 1 << 20
BATCH = 1 << 15
NNZ = 26
STEPS = 8
LR = 0.5
cfg = SGDConfig(learning_rate=LR, tol=0)
print("backend:", jax.default_backend(), flush=True)


@jax.jit
def gen(key):
    kc, kd, ky = jax.random.split(key, 3)
    y = jax.random.bernoulli(ky, 0.5, (STEPS, BATCH)).astype(jnp.float32)
    cat = jax.random.randint(kc, (STEPS, BATCH, NNZ), 32, D, jnp.int32)
    cat = cat.at[:, :, 0].set(jnp.where(y == 1, 16, 17))
    dense = jax.random.normal(kd, (STEPS, BATCH, 13), jnp.float32)
    return dense, cat, y


dense, cat, y = gen(jax.random.PRNGKey(0))
lay = ell_layout_device(cat, D, ovf_cap=1 << 13).assert_capacities().trim_overflow()
np.asarray(lay.ovf_idx[0, :1])
extra = (lay.src, lay.pos, lay.mask, lay.ovf_idx, lay.ovf_src,
         lay.heavy_idx, lay.heavy_cnt)
M_LEN = _ext_len(BATCH)


def fresh():
    return {"w": jnp.zeros((D,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


def make_loop(update):
    def maker(n_epochs):
        @jax.jit
        def run(params, dense, y, *ex):
            ones = jnp.ones(y.shape, jnp.float32)

            def epoch(params, _):
                def step(params, i):
                    e = tuple(a[i] for a in ex)
                    return update(params, dense[i], *e, y[i], ones[i])
                p, losses = jax.lax.scan(step, params, jnp.arange(STEPS))
                return p, jnp.mean(losses)
            return jax.lax.scan(epoch, params, None, length=n_epochs)
        return run
    return maker


def fit_cost(loop_maker, args, reps=(2, 10)):
    ts = []
    for n in reps:
        run = loop_maker(n)
        out = run(*args)
        np.asarray(out[0]["w"]).ravel()[:1]
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = run(*args)
            np.asarray(out[0]["w"]).ravel()[:1]
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    return (ts[1] - ts[0]) / ((reps[1] - reps[0]) * STEPS)


args = (fresh(), dense, y) + extra

t_full = fit_cost(make_loop(_mixed_update_ell(logistic_loss, cfg)), args)
print(f"{'planned step (full)':26s} {t_full*1e3:7.2f} ms/step", flush=True)


def make_ablated(margin_k=True, margin_oh=True, scatter_k=True, ovf=True,
                 heavy=True, dense_on=True):
    def update(params, dense_b, src, pos, mask, oi, osrc, hi, hc, yb, wb):
        w, b = params["w"], params["b"]
        nd = dense_b.shape[-1]
        margin = (dense_b @ w[:nd] + b) if dense_on else jnp.broadcast_to(
            b, (BATCH,))
        if margin_k:
            mext = ell_margin_fused(w, src, pos, mask, m_len=M_LEN)
            if margin_oh:
                mext = mext.at[osrc].add(w[oi], mode="drop")
                margin = margin + mext[:BATCH] + w[hi] @ hc.astype(
                    jnp.float32)
            else:
                margin = margin + mext[:BATCH]
        value, pull = jax.vjp(lambda m: logistic_loss(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))
        r_ext = _extended_r(r)
        if scatter_k:
            w = ell_scatter_apply_fused(w, r_ext, src, pos, mask, lr=LR)
        else:
            w = w + jnp.sum(r_ext) * 1e-20
        if ovf:
            w = w.at[oi].add((-LR) * r_ext[osrc])
        if heavy:
            w = w.at[hi].add((-LR) * (hc.astype(jnp.float32) @ r))
        if dense_on:
            w = w.at[:nd].add(-LR * (r @ dense_b))
            b = b - LR * jnp.sum(r)
        return {"w": w, "b": b}, value
    return update


for name, off in [
    ("inline full", {}),
    ("- margin kernel", {"margin_k": False, "margin_oh": False}),
    ("- margin ovf+heavy", {"margin_oh": False}),
    ("- scatter kernel", {"scatter_k": False}),
    ("- grad ovf", {"ovf": False}),
    ("- grad heavy", {"heavy": False}),
    ("- dense+bias", {"dense_on": False}),
    ("kernels only", {"margin_oh": False, "ovf": False, "heavy": False,
                      "dense_on": False}),
    ("loss only", {"margin_k": False, "margin_oh": False,
                   "scatter_k": False, "ovf": False, "heavy": False,
                   "dense_on": False}),
]:
    t = fit_cost(make_loop(make_ablated(**off)), args)
    print(f"{name:26s} {t*1e3:7.2f} ms/step", flush=True)


# ---- standalone kernel timings (outside the scan) -------------------------
w0 = jnp.zeros((D,), jnp.float32)
r_ext0 = _extended_r(jnp.ones((BATCH,), jnp.float32) * 1e-5)
src0, pos0, mask0 = lay.src[0], lay.pos[0], lay.mask[0]


def time_op(fn, *a):
    out = fn(*a)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*a))
        best = min(best, time.perf_counter() - t0)
    return best


t = time_op(lambda: ell_margin_fused(w0, src0, pos0, mask0, m_len=M_LEN))
print(f"{'margin kernel alone':26s} {t*1e3:7.2f} ms/call "
      "(incl dispatch)", flush=True)
t = time_op(lambda: ell_scatter_apply_fused(w0, r_ext0, src0, pos0, mask0,
                                            lr=LR))
print(f"{'scatter kernel alone':26s} {t*1e3:7.2f} ms/call "
      "(incl dispatch)", flush=True)
