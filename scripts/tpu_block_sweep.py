"""Sweep the fused kernels' block size (grid rows per Mosaic step) on
real TPU: fewer grid steps amortize per-block overhead; VMEM transients
((r_rows, 128) one-hot per row-iteration) are block-size-independent.

Run: timeout 1800 python -u scripts/tpu_block_sweep.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np

import flink_ml_tpu.ops.ell_scatter as E
from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import SGDConfig, _mixed_update_ell

D = 1 << 20
BATCH = 1 << 15
NNZ = 26
STEPS = 8
LR = 0.5
cfg = SGDConfig(learning_rate=LR, tol=0)
print("backend:", jax.default_backend(), flush=True)


@jax.jit
def gen(key):
    kc, kd, ky = jax.random.split(key, 3)
    y = jax.random.bernoulli(ky, 0.5, (STEPS, BATCH)).astype(jnp.float32)
    cat = jax.random.randint(kc, (STEPS, BATCH, NNZ), 32, D, jnp.int32)
    cat = cat.at[:, :, 0].set(jnp.where(y == 1, 16, 17))
    dense = jax.random.normal(kd, (STEPS, BATCH, 13), jnp.float32)
    return dense, cat, y


dense, cat, y = gen(jax.random.PRNGKey(0))
lay = E.ell_layout_device(cat, D, ovf_cap=1 << 13) \
    .assert_capacities().trim_overflow()
np.asarray(lay.ovf_idx[0, :1])
extra = (lay.src, lay.pos, lay.mask, lay.ovf_idx, lay.ovf_src,
         lay.heavy_idx, lay.heavy_cnt)


def fresh():
    return {"w": jnp.zeros((D,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


def make_loop(update):
    def maker(n_epochs):
        @jax.jit
        def run(params, dense, y, *ex):
            ones = jnp.ones(y.shape, jnp.float32)

            def epoch(params, _):
                def step(params, i):
                    e = tuple(a[i] for a in ex)
                    return update(params, dense[i], *e, y[i], ones[i])
                p, losses = jax.lax.scan(step, params, jnp.arange(STEPS))
                return p, jnp.mean(losses)
            return jax.lax.scan(epoch, params, None, length=n_epochs)
        return run
    return maker


def fit_cost(loop_maker, args, reps=(2, 10)):
    ts = []
    for n in reps:
        run = loop_maker(n)
        out = run(*args)
        np.asarray(out[0]["w"]).ravel()[:1]
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = run(*args)
            np.asarray(out[0]["w"]).ravel()[:1]
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    return (ts[1] - ts[0]) / ((reps[1] - reps[0]) * STEPS)


args = (fresh(), dense, y) + extra
base = None
for br in (8, 16, 32):
    E._FUSED_BLOCK_ROWS = br
    # fresh jit caches per block size: the kernels key on their closure
    E.ell_scatter_apply_fused.clear_cache()
    E.ell_margin_fused.clear_cache()
    t = fit_cost(make_loop(_mixed_update_ell(logistic_loss, cfg)), args)
    base = base or t
    print(f"block_rows={br:3d}  {t*1e3:6.2f} ms/step  "
          f"({t/base:.2f}x of br=8)", flush=True)
