#!/bin/bash
# Round-5 TPU measurement campaign — run the moment the relay is up.
# Order per R5_TPU_STATUS.md: kernel tier gates timing; headline bench
# extends the r4 band; probes decide the armed chip verdicts.
# Usage: bash scripts/r5_campaign.sh [run_number]
set -u
cd "$(dirname "$0")/.."
N="${1:-1}"

echo "== 0. relay probe (90 s cap)"
timeout 90 python -c "import jax; print(jax.devices())" || {
    echo "RELAY DOWN — aborting campaign"; exit 1; }

echo "== 1. TPU kernel tier (gates all timing)"
python -m pytest tests_tpu/ -m tpu -q | tail -3 || {
    echo "KERNEL TIER RED — fix before timing"; exit 1; }

# short-window ordering: the round's decision measurements (minutes)
# run BEFORE the full bench (~15-20 min) so a brief relay window still
# answers the armed verdicts
echo "== 2. WDL step shootout (the r5 headline decision)"
python scripts/wdl_step_experiments.py | tee "TPU_WDL_SHOOTOUT_r05.json"

echo "== 3. put-overlap probe"
python scripts/put_overlap_probe.py | tee "TPU_PUT_PROBE_r05.json"

echo "== 4. full bench -> TPU_BENCH_r05_run${N}.json"
python bench.py > "TPU_BENCH_r05_run${N}.json" 2> "TPU_BENCH_r05_run${N}.err"
tail -1 "TPU_BENCH_r05_run${N}.json"

echo "== campaign run ${N} done; record verdicts in R5_TPU_STATUS.md"
