"""ELL mixed-LR step ablation on real TPU (VERDICT r3 task 2).

Attributes the r3-unexplained gap (full ELL epoch measured 10.2 ms/step
vs ~4 ms predicted from piecewise kernel timings) by dropping one piece
of the step at a time inside the SAME fused epoch loop used for timing.
Two-point fits over epoch counts cancel the fixed tunnel round-trip, and
every timed op's inputs depend on the scan carry (nothing hoistable —
see the r3 measurement-traps notes).

Run (writes stdout; tee to TPU_ABLATION_r04.txt):
    timeout 1800 python -u scripts/tpu_ablation.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import (
    SGDConfig,
    _gather_weights,
    _mixed_update,
    _mixed_update_ell,
    resolve_global_batch_size,
)
from flink_ml_tpu.ops.ell_scatter import ell_layout_device, ell_scatter_apply

D = 1 << 20
BATCH = 1 << 15
NNZ = 26
STEPS = 8
LR = 0.5
cfg = SGDConfig(learning_rate=LR, tol=0)

print("backend:", jax.default_backend(), flush=True)
print("auto batch at bench shape:",
      resolve_global_batch_size(SGDConfig(), 1_000_000, D), flush=True)


@jax.jit
def gen(key):
    kc, kd, ky = jax.random.split(key, 3)
    y = jax.random.bernoulli(ky, 0.5, (STEPS, BATCH)).astype(jnp.float32)
    cat = jax.random.randint(kc, (STEPS, BATCH, NNZ), 32, D, jnp.int32)
    cat = cat.at[:, :, 0].set(jnp.where(y == 1, 16, 17))
    dense = jax.random.normal(kd, (STEPS, BATCH, 13), jnp.float32)
    return dense, cat, y


dense, cat, y = gen(jax.random.PRNGKey(0))
t0 = time.perf_counter()
lay = ell_layout_device(cat, D, ovf_cap=1 << 13).assert_capacities().trim_overflow()
np.asarray(lay.ovf_idx[0, :1])
print(f"layout build {time.perf_counter()-t0:.1f}s  "
      f"need_ovf={int(np.asarray(lay.need_ovf).max())} "
      f"need_heavy={int(np.asarray(lay.need_heavy).max())}", flush=True)
extra = (lay.src, lay.pos, lay.mask, lay.ovf_idx, lay.ovf_src,
         lay.heavy_idx, lay.heavy_cnt)


def fresh():
    return {"w": jnp.zeros((D,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


def fit_cost(loop_maker, args, reps=(2, 10)):
    """Two-point fit over EPOCH counts (each epoch = STEPS steps)."""
    ts = []
    for n in reps:
        run = loop_maker(n)
        out = run(*args)
        np.asarray(out[0]["w"]).ravel()[:1]
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = run(*args)
            np.asarray(out[0]["w"]).ravel()[:1]
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    return (ts[1] - ts[0]) / ((reps[1] - reps[0]) * STEPS)


def make_loop(update, with_cat=True):
    # with_cat=False: the r4 planned ELL update reads no raw cat tensor
    def maker(n_epochs):
        @jax.jit
        def run(params, dense, cat, y, *ex):
            ones = jnp.ones(y.shape, jnp.float32)

            def epoch(params, _):
                def step(params, i):
                    e = tuple(a[i] for a in ex)
                    lead = (dense[i], cat[i]) if with_cat else (dense[i],)
                    return update(params, *lead, *e, y[i],
                                  ones[i])
                p, losses = jax.lax.scan(step, params, jnp.arange(STEPS))
                return p, jnp.mean(losses)
            return jax.lax.scan(epoch, params, None, length=n_epochs)
        return run
    return maker


args_base = (fresh(), dense, cat, y)
t = fit_cost(make_loop(_mixed_update(logistic_loss, cfg)), args_base)
print(f"oracle (XLA blocked)        {t*1e3:7.2f} ms/step", flush=True)
t_ell = fit_cost(make_loop(_mixed_update_ell(logistic_loss, cfg),
                           with_cat=False),
                 args_base + extra)
print(f"ELL planned path            {t_ell*1e3:7.2f} ms/step  "
      f"-> {1.0/(t_ell*32):5.2f} epochs/s @32steps", flush=True)


# ---- ablation: drop pieces of the ELL step -------------------------------
def make_ablated(margin_on, ugather_on, kernel_on, ovf_on, heavy_on):
    def update(params, dense_b, cat_b, src, pos, mask, oi, osrc, hi, hc,
               yb, wb):
        w, b = params["w"], params["b"]
        nd = dense_b.shape[-1]
        if margin_on:
            margin = (dense_b @ w[:nd]
                      + jnp.sum(_gather_weights(w, cat_b), axis=-1) + b)
        else:
            margin = dense_b @ w[:nd] + b
        value, pull = jax.vjp(lambda m: logistic_loss(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))
        pad = 256 - (BATCH % 256) or 256
        r_ext = jnp.concatenate([r, jnp.zeros((pad,), jnp.float32)])
        if ugather_on:
            u = (-LR) * _gather_weights(r_ext, src)
        else:
            u = jnp.broadcast_to(r_ext[0], src.shape) * (-LR)
        if kernel_on:
            w = ell_scatter_apply(w, u, pos, mask)
        else:
            w = w + jnp.sum(u) * 1e-20
        if ovf_on:
            w = w.at[oi].add((-LR) * r_ext[osrc])
        if heavy_on:
            w = w.at[hi].add((-LR) * (hc.astype(jnp.float32) @ r))
        w = w.at[:nd].add(-LR * (r @ dense_b))
        b = b - LR * jnp.sum(r)
        return {"w": w, "b": b}, value
    return update


ON = dict(margin_on=True, ugather_on=True, kernel_on=True, ovf_on=True,
          heavy_on=True)
for name, off in [
    ("full", {}),
    ("- margin gather", {"margin_on": False}),
    ("- u gather", {"ugather_on": False}),
    ("- kernel", {"kernel_on": False}),
    ("- overflow scatter", {"ovf_on": False}),
    ("- heavy matvec", {"heavy_on": False}),
    ("bare margin+loss", {"ugather_on": False, "kernel_on": False,
                          "ovf_on": False, "heavy_on": False}),
]:
    t = fit_cost(make_loop(make_ablated(**{**ON, **off})),
                 args_base + extra)
    print(f"{name:26s} {t*1e3:7.2f} ms/step", flush=True)


# ---- gather-implementation variants --------------------------------------
# The r3 gap's prime suspect is the u-gather (r_ext[src]); the blocked
# 256-lane row-gather wins MICRObenchmarks (1.7 vs 6-7 ns/slot
# elementwise), but inside the fused step XLA may materialize the
# blocked path's (slots, lanes) intermediates.  One timed leg per
# implementation of each gather answers it.
def _blocked_gather_lanes(w, idx, lanes):
    flat = idx.reshape(-1)
    hi, lo = flat // lanes, flat % lanes
    onehot = lo[:, None] == jnp.arange(lanes, dtype=lo.dtype)[None, :]
    rows_ = w.reshape(-1, lanes)[hi]
    return jnp.sum(jnp.where(onehot, rows_, 0), axis=-1).reshape(idx.shape)


def make_gather_variant(u_mode, margin_mode):
    def update(params, dense_b, cat_b, src, pos, mask, oi, osrc, hi, hc,
               yb, wb):
        w, b = params["w"], params["b"]
        nd = dense_b.shape[-1]
        if margin_mode == "blocked":
            mg = jnp.sum(_gather_weights(w, cat_b), axis=-1)
        else:
            mg = jnp.sum(w[cat_b], axis=-1)
        margin = dense_b @ w[:nd] + mg + b
        value, pull = jax.vjp(lambda m: logistic_loss(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))
        pad = 256 - (BATCH % 256) or 256
        r_ext = jnp.concatenate([r, jnp.zeros((pad,), jnp.float32)])
        if u_mode == "blocked256":
            u = (-LR) * _gather_weights(r_ext, src)
        elif u_mode == "blocked128":
            u = (-LR) * _blocked_gather_lanes(r_ext, src, 128)
        else:
            u = (-LR) * r_ext[src]
        w = ell_scatter_apply(w, u, pos, mask)
        w = w.at[oi].add((-LR) * r_ext[osrc])
        w = w.at[hi].add((-LR) * (hc.astype(jnp.float32) @ r))
        w = w.at[:nd].add(-LR * (r @ dense_b))
        b = b - LR * jnp.sum(r)
        return {"w": w, "b": b}, value
    return update


print("--- gather variants (full step, one knob changed) ---", flush=True)
for u_mode in ("blocked256", "blocked128", "elementwise"):
    t = fit_cost(make_loop(make_gather_variant(u_mode, "blocked")),
                 args_base + extra)
    print(f"u={u_mode:12s} margin=blocked    {t*1e3:7.2f} ms/step",
          flush=True)
for margin_mode in ("elementwise",):
    t = fit_cost(make_loop(make_gather_variant("blocked256", margin_mode)),
                 args_base + extra)
    print(f"u=blocked256   margin={margin_mode:12s} {t*1e3:6.2f} ms/step",
          flush=True)


# ---- EXPERIMENTAL fused-gather kernel -------------------------------------
# Replaces u-gather + kernel with one Mosaic call (one-hot MXU
# contraction in-kernel).  If the u-gather dominates the ablation above,
# this leg is the candidate fix; ~0.35 ms/step of MXU work instead of
# the ~2-2.5 ms transaction-bound gather.
from flink_ml_tpu.models.common.sgd import _extended_r
from flink_ml_tpu.ops.ell_scatter import ell_scatter_apply_fused


def make_fused(margin_on=True):
    def update(params, dense_b, cat_b, src, pos, mask, oi, osrc, hi, hc,
               yb, wb):
        w, b = params["w"], params["b"]
        nd = dense_b.shape[-1]
        if margin_on:
            margin = (dense_b @ w[:nd]
                      + jnp.sum(_gather_weights(w, cat_b), axis=-1) + b)
        else:
            margin = dense_b @ w[:nd] + b
        value, pull = jax.vjp(lambda m: logistic_loss(m, yb, wb), margin)
        (r,) = pull(jnp.ones_like(value))
        r_ext = _extended_r(r)
        w = ell_scatter_apply_fused(w, r_ext, src, pos, mask, lr=LR)
        w = w.at[oi].add((-LR) * r_ext[osrc])
        w = w.at[hi].add((-LR) * (hc.astype(jnp.float32) @ r))
        w = w.at[:nd].add(-LR * (r @ dense_b))
        b = b - LR * jnp.sum(r)
        return {"w": w, "b": b}, value
    return update


print("--- fused-gather kernel (experimental) ---", flush=True)
try:
    t = fit_cost(make_loop(make_fused()), args_base + extra)
    print(f"fused gather+kernel        {t*1e3:7.2f} ms/step", flush=True)
    t = fit_cost(make_loop(make_fused(margin_on=False)), args_base + extra)
    print(f"fused, - margin gather     {t*1e3:7.2f} ms/step", flush=True)
except Exception as exc:  # noqa: BLE001 - Mosaic compile risk, keep going
    print(f"fused kernel leg failed: {exc!r}"[:300], flush=True)
