#!/usr/bin/env python
"""Static guard: no host syncs inside scan-body / step functions
(ISSUE 6 satellite).

The communication-overlap schedule (``grad_reduce.pipelined_reduce``)
only buys anything if the device queue stays full: a host
synchronization inside a step body — ``block_until_ready``,
``jax.device_get``, ``np.asarray`` on a traced value, ``.item()`` —
fences the dispatch stream and silently destroys the overlap (and the
chunked-dispatch amortization of PR 1 with it).  This pass parses every
module under ``flink_ml_tpu/models/`` and ``flink_ml_tpu/parallel/``
and flags those calls inside functions that are (a) named like step /
scan bodies (``update``, ``batch_step``, ``device_fn``, ``*_step``,
``*_body``, ...) or (b) passed as the scanned body to ``lax.scan`` /
``masked_chunk_scan`` anywhere in the module — nested helper defs
inside a step body are covered by the AST walk.

Heuristic by design (AST names, not tracing), tuned to this repo's
idiom: step bodies are pure device math here, so ANY of the four calls
is a finding.  A justified host sync goes in the explicit allowlist
below with a reason.

Run with no arguments to check the two subsystems; pass explicit paths
to check those instead.  Exit 0 = clean, 1 = findings (one line each).
Wired into tier-1 via tests/test_no_host_sync.py.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every step/scan body in these trees must stay host-sync-free
#: (``online/`` joined with ISSUE 7: its driver feeds the same chunked
#: scan, so a host sync in a step-named helper there would fence the
#: training dispatch stream the publishes ride on)
SCAN_ROOTS = [
    "flink_ml_tpu/models",
    "flink_ml_tpu/online",
    "flink_ml_tpu/parallel",
]

#: (file, function) pairs exempt with a reason — currently none.
ALLOWLIST: dict = {}

#: function names that ARE step/scan bodies in this repo's idiom
STEP_NAMES = {
    "update", "batch_step", "scan_step", "chunk_step", "device_fn",
    "train_step", "epoch_body", "body", "step",
}

STEP_SUFFIXES = ("_step", "_body", "_update")

#: callables whose first argument is a scanned/stepped body
SCAN_CALLEES = {"scan", "masked_chunk_scan", "while_loop", "fori_loop"}


def _call_name(call: ast.Call):
    """Trailing name of the called expression: ``lax.scan`` -> "scan"."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_step_name(name: str) -> bool:
    return name in STEP_NAMES or name.endswith(STEP_SUFFIXES)


def _scanned_body_names(tree: ast.AST) -> set:
    """Names passed as the body argument to scan-family calls anywhere in
    the module (``lax.scan(step_fn, ...)``, ``fori_loop(lo, hi, body,
    ...)``) — those functions are step bodies regardless of their name."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in SCAN_CALLEES or not node.args:
            continue
        args = node.args
        cands = [args[2]] if name == "fori_loop" and len(args) >= 3 \
            else args[:2] if name == "while_loop" else [args[0]]
        for cand in cands:
            if isinstance(cand, ast.Name):
                out.add(cand.id)
    return out


def _sync_finding(call: ast.Call):
    """The host-sync kind of a call, or None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready":
            return "block_until_ready"
        if f.attr == "item":
            return ".item()"
        if f.attr == "device_get":
            return "jax.device_get"
        if f.attr == "asarray" and isinstance(f.value, ast.Name) \
                and f.value.id in ("np", "numpy", "onp"):
            return "np.asarray"
    elif isinstance(f, ast.Name) and f.id == "device_get":
        return "device_get"
    return None


def check_file(path: str) -> list:
    src = open(path).read()
    tree = ast.parse(src, filename=path)
    rel = os.path.relpath(path, REPO)
    scanned = _scanned_body_names(tree)
    problems = []
    seen: set = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not (_is_step_name(fn.name) or fn.name in scanned):
            continue
        if (rel, fn.name) in ALLOWLIST:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            kind = _sync_finding(node)
            if kind and (rel, node.lineno) not in seen:
                seen.add((rel, node.lineno))
                problems.append(
                    f"{rel}:{node.lineno}: {kind} inside step body "
                    f"{fn.name}() — a host sync here fences the dispatch "
                    "stream and destroys comm/compute overlap")
    return problems


def _module_paths() -> list:
    paths = []
    for root in SCAN_ROOTS:
        for dirpath, _dirnames, filenames in os.walk(
                os.path.join(REPO, root)):
            for f in sorted(filenames):
                if f.endswith(".py"):
                    paths.append(os.path.join(dirpath, f))
    return paths


def main(argv) -> int:
    paths = argv or _module_paths()
    problems = []
    for path in paths:
        problems += check_file(path)
    for p in problems:
        print(f"HOST SYNC IN STEP BODY: {p}")
    if not problems:
        print(f"host-sync discipline clean ({len(paths)} module(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
