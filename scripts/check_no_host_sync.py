#!/usr/bin/env python
"""DEPRECATED shim — the host-sync check now lives in graftlint.

The real pass is ``scripts/graftlint/passes/host_sync.py``; run it (and
every other pass) with::

    python -m scripts.graftlint

This file keeps the legacy surface (``SCAN_ROOTS``, ``_module_paths``,
``check_file``, CLI) alive for existing callers and
``tests/test_no_host_sync.py``, delegating every check to the
framework-hosted pass so there is exactly ONE implementation.
"""

from __future__ import annotations

import os
import sys
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts.graftlint.core import (  # noqa: E402
    ModuleInfo,
    Project,
    iter_py_files,
)
from scripts.graftlint.passes.host_sync import (  # noqa: E402
    SCAN_ROOTS as _ROOTS,
    HostSyncPass,
)

#: legacy name (a list, as before); the pass's tuple is canonical
SCAN_ROOTS = list(_ROOTS)

_pass = HostSyncPass()
_project = Project(repo=REPO)


def check_file(path: str) -> list:
    """Problem strings for one module, in the legacy one-line format.
    Inline ``# graftlint: disable=host-sync`` suppressions are honored,
    so this surface and the canonical gate agree on what is clean."""
    mod = ModuleInfo(path, REPO)
    return [f"{f.path}:{f.line}: {f.message}"
            for f in _pass.check_module(mod, _project)
            if not {_pass.id, "all"} & mod.suppressions.get(f.line, set())]


def _module_paths() -> list:
    return list(iter_py_files([os.path.join(REPO, r) for r in SCAN_ROOTS]))


def main(argv) -> int:
    warnings.warn(
        "scripts/check_no_host_sync.py is a shim; use "
        "`python -m scripts.graftlint` (pass id: host-sync)",
        DeprecationWarning, stacklevel=2)
    paths = argv or _module_paths()
    problems = []
    for path in paths:
        problems += check_file(path)
    for p in problems:
        print(f"HOST SYNC IN STEP BODY: {p}")
    if not problems:
        print(f"host-sync discipline clean ({len(paths)} module(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
