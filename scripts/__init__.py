"""Repo tooling.  The package exists so ``python -m scripts.graftlint``
resolves from the repo root; nothing here is shipped (pyproject packaging
includes ``flink_ml_tpu*`` only)."""
