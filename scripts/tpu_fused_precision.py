"""Pick SGDConfig.ell_precision on real TPU (r4 follow-up to the ablation).

Times the planned mixed-ELL step with the fused kernel at each MXU
precision against the gather+kernel pair and the XLA oracle, and checks
epoch-level weight parity vs the oracle at the bench's pre-timing
tolerance (rtol=1e-3, atol=1e-4, bench.py:243) — the precision the
planner defaults to must pass it.

Run: timeout 1800 python -u scripts/tpu_fused_precision.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np

import flink_ml_tpu.models.common.sgd as sgd
from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import (
    SGDConfig,
    _mixed_update,
    _mixed_update_ell,
)
from flink_ml_tpu.ops.ell_scatter import ell_layout_device

D = 1 << 20
BATCH = 1 << 15
NNZ = 26
STEPS = 8
LR = 0.5
cfg = SGDConfig(learning_rate=LR, tol=0)

print("backend:", jax.default_backend(), flush=True)


@jax.jit
def gen(key):
    kc, kd, ky = jax.random.split(key, 3)
    y = jax.random.bernoulli(ky, 0.5, (STEPS, BATCH)).astype(jnp.float32)
    cat = jax.random.randint(kc, (STEPS, BATCH, NNZ), 32, D, jnp.int32)
    cat = cat.at[:, :, 0].set(jnp.where(y == 1, 16, 17))
    dense = jax.random.normal(kd, (STEPS, BATCH, 13), jnp.float32)
    return dense, cat, y


dense, cat, y = gen(jax.random.PRNGKey(0))
lay = ell_layout_device(cat, D, ovf_cap=1 << 13).assert_capacities().trim_overflow()
np.asarray(lay.ovf_idx[0, :1])
extra = (lay.src, lay.pos, lay.mask, lay.ovf_idx, lay.ovf_src,
         lay.heavy_idx, lay.heavy_cnt)


def fresh():
    return {"w": jnp.zeros((D,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


def make_loop(update, with_cat=True):
    # with_cat=False: the r4 planned ELL update reads no raw cat tensor
    def maker(n_epochs):
        @jax.jit
        def run(params, dense, cat, y, *ex):
            ones = jnp.ones(y.shape, jnp.float32)

            def epoch(params, _):
                def step(params, i):
                    e = tuple(a[i] for a in ex)
                    lead = (dense[i], cat[i]) if with_cat else (dense[i],)
                    return update(params, *lead, *e, y[i],
                                  ones[i])
                p, losses = jax.lax.scan(step, params, jnp.arange(STEPS))
                return p, jnp.mean(losses)
            return jax.lax.scan(epoch, params, None, length=n_epochs)
        return run
    return maker


def fit_cost(loop_maker, args, reps=(2, 10)):
    ts = []
    for n in reps:
        run = loop_maker(n)
        out = run(*args)
        np.asarray(out[0]["w"]).ravel()[:1]
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = run(*args)
            np.asarray(out[0]["w"]).ravel()[:1]
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    return (ts[1] - ts[0]) / ((reps[1] - reps[0]) * STEPS)


args_base = (fresh(), dense, cat, y)
args_ell = args_base + extra

# one-epoch oracle weights for the parity check
oracle_run = make_loop(_mixed_update(logistic_loss, cfg))(1)
w_ora = np.asarray(oracle_run(*args_base)[0]["w"])

legs = []
for name, prec in [("fused/default", "default"), ("fused/highest", "highest")]:
    cfg_p = SGDConfig(learning_rate=LR, tol=0, ell_precision=prec)
    upd = _mixed_update_ell(logistic_loss, cfg_p, backend="pallas")
    w_got = np.asarray(make_loop(upd, with_cat=False)(1)(*args_ell)[0]["w"])
    ok = np.allclose(w_got, w_ora, rtol=1e-3, atol=1e-4)
    err = float(np.max(np.abs(w_got - w_ora)))
    t = fit_cost(make_loop(upd, with_cat=False), args_ell)
    legs.append((name, t, ok, err))
    print(f"{name:16s} {t*1e3:7.2f} ms/step  bench-parity={ok} "
          f"max|dw|={err:.2e}", flush=True)

# the pre-r4 planned path: XLA u-gather + scatter kernel (force the
# fallback branch by an off-8 grid? no — call the pair directly)
from flink_ml_tpu.models.common.sgd import (_extended_r, _gather_weights,
                                            _finish_sparse_step)
from flink_ml_tpu.ops.ell_scatter import ell_scatter_apply


def pair_update(params, dense_b, cat_b, src, pos, mask, oi, osrc, hi, hc,
                yb, wb):
    finish = _finish_sparse_step(cfg)
    w, b = params["w"], params["b"]
    nd = dense_b.shape[-1]
    margin = (dense_b @ w[:nd]
              + jnp.sum(_gather_weights(w, cat_b), axis=-1) + b)
    value, pull = jax.vjp(lambda m: logistic_loss(m, yb, wb), margin)
    (r,) = pull(jnp.ones_like(value))
    r_ext = _extended_r(r)

    def apply_grad(w):
        u = (-LR) * _gather_weights(r_ext, src)
        w = ell_scatter_apply(w, u, pos, mask)
        w = w.at[oi].add((-LR) * r_ext[osrc])
        w = w.at[hi].add((-LR) * (hc.astype(jnp.float32) @ r))
        return w.at[:nd].add(-LR * (r @ dense_b))

    return finish(w, b, value, r, apply_grad)


t = fit_cost(make_loop(pair_update), args_ell)
print(f"{'gather+kernel':16s} {t*1e3:7.2f} ms/step  (pre-r4 planned path)",
      flush=True)
t = fit_cost(make_loop(_mixed_update(logistic_loss, cfg)), args_base)
print(f"{'XLA oracle':16s} {t*1e3:7.2f} ms/step", flush=True)
