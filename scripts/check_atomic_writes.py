#!/usr/bin/env python
"""DEPRECATED shim — the atomic-writes check now lives in graftlint.

The real pass is ``scripts/graftlint/passes/atomic_writes.py``; run it
(and every other pass) with::

    python -m scripts.graftlint

This file keeps the legacy surface (``DURABLE_MODULES``, ``check_file``,
CLI) alive for existing callers and ``tests/test_atomic_writes.py``,
delegating to the framework-hosted pass (inline suppressions included).
NOTE the legacy module list is frozen at the original three files; the
pass additionally guards ``robustness/durability.py``.
"""

from __future__ import annotations

import os
import sys
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts.graftlint.core import ModuleInfo, Project  # noqa: E402
from scripts.graftlint.passes.atomic_writes import (  # noqa: E402
    AtomicWritesPass,
)

#: the legacy durable-module list (frozen; see module docstring)
DURABLE_MODULES = [
    "flink_ml_tpu/utils/persist.py",
    "flink_ml_tpu/iteration/checkpoint.py",
    "flink_ml_tpu/data/wal.py",
]

_pass = AtomicWritesPass()
_project = Project(repo=REPO)


def check_file(path: str) -> list:
    """Problem strings for one module, in the legacy one-line format.
    Inline ``# graftlint: disable=atomic-writes`` suppressions are
    honored, so this surface and the canonical gate agree on what is
    clean (the two protocol-level exceptions in
    ``robustness/durability.py`` stay quiet here too)."""
    mod = ModuleInfo(path, REPO)
    return [f"{f.path}:{f.line}: {f.message}"
            for f in _pass.check_module(mod, _project)
            if not {_pass.id, "all"} & mod.suppressions.get(f.line, set())]


def main(argv) -> int:
    warnings.warn(
        "scripts/check_atomic_writes.py is a shim; use "
        "`python -m scripts.graftlint` (pass id: atomic-writes)",
        DeprecationWarning, stacklevel=2)
    paths = argv or [os.path.join(REPO, m) for m in DURABLE_MODULES]
    problems = []
    for path in paths:
        problems += check_file(path)
    for p in problems:
        print(f"NON-ATOMIC WRITE: {p}")
    if not problems:
        print(f"atomic-write discipline clean ({len(paths)} module(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
