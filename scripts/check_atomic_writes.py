#!/usr/bin/env python
"""Static guard: durable-layer writes must be atomic (ISSUE 5 satellite).

The durability contract of ``utils/persist.py``, ``iteration/
checkpoint.py`` and ``data/wal.py`` is *write tmp -> os.replace*: a
crash mid-write must never leave a half-written file at a path a loader
trusts.  This pass parses each module and flags any ``open(path, "w")``
/ ``open(path, "wb")`` call whose enclosing function does not later (or
anywhere, same function) call ``os.replace`` on a path sharing a
variable with the opened expression — the pattern that makes the write
atomic (writing INTO a tmp dir that is itself renamed counts: the
shared variable is the tmp dir name).

Heuristic by design (AST names, not dataflow), tuned to this repo's
idiom; a false positive is fixed by actually making the write atomic or
adding the path to the explicit allowlist below with a justification.

Run with no arguments to check the three durable modules; pass explicit
paths to check those instead.  Exit 0 = clean, 1 = findings (one line
each).  Wired into tier-1 via tests/test_atomic_writes.py.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the durable layer: every open-for-write here must be atomic
DURABLE_MODULES = [
    "flink_ml_tpu/utils/persist.py",
    "flink_ml_tpu/iteration/checkpoint.py",
    "flink_ml_tpu/data/wal.py",
]

#: (file, function) pairs exempt with a reason — currently none.
ALLOWLIST: dict = {}

_WRITE_MODES = {"w", "wb", "w+", "wb+", "a", "ab"}


def _names(node: ast.AST) -> set:
    """Variable names referenced by an expression, skipping attribute
    roots used as call targets (``os`` in ``os.path.join(tmp, ...)``)."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
    out.discard("os")
    return out


def _open_mode(call: ast.Call):
    """The literal mode of an ``open(...)`` call, or None."""
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _is_open(call: ast.Call) -> bool:
    return isinstance(call.func, ast.Name) and call.func.id == "open"


def _is_os_replace(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "replace"
            and isinstance(f.value, ast.Name) and f.value.id == "os")


def check_file(path: str) -> list:
    src = open(path).read()
    tree = ast.parse(src, filename=path)
    rel = os.path.relpath(path, REPO)
    problems = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if (rel, fn.name) in ALLOWLIST:
            continue
        writes = []     # (lineno, path-variable names)
        replaced = set()  # names appearing as os.replace source args
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_open(node):
                mode = _open_mode(node)
                if mode and mode.strip("b+") in ("w", "a") \
                        and mode in _WRITE_MODES and node.args:
                    writes.append((node.lineno, _names(node.args[0])))
            elif _is_os_replace(node) and node.args:
                replaced |= _names(node.args[0])
        for lineno, names in writes:
            if not names:
                problems.append(
                    f"{rel}:{lineno}: open-for-write on a literal path "
                    "with no os.replace — not crash-atomic")
            elif not names & replaced:
                problems.append(
                    f"{rel}:{lineno}: open-for-write on {sorted(names)} "
                    f"but {fn.name}() never os.replace's a path sharing "
                    "those names — a crash can leave a half-written file")
    return problems


def main(argv) -> int:
    paths = argv or [os.path.join(REPO, m) for m in DURABLE_MODULES]
    problems = []
    for path in paths:
        problems += check_file(path)
    for p in problems:
        print(f"NON-ATOMIC WRITE: {p}")
    if not problems:
        print(f"atomic-write discipline clean ({len(paths)} module(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
