#!/usr/bin/env python
"""Guard against silent bench-schema drift (ISSUE 3 satellite).

Two checks, both cheap enough for tier-1:

1. **Metric-version cross-check** — every ``*metric_version`` literal in
   ``bench.py`` must appear in BENCH_SCHEMA.md's "Metric versions" table
   with the SAME value, and vice versa.  This is exactly the failure mode
   of the r6/r7 bumps: the version moved in code, the contract doc
   lagged, and downstream parsers compared across incompatible series.

2. **Emitted-key validation** — given ``BENCH_*.json`` paths (raw bench
   stdout lines, or the driver's capture files whose ``parsed`` object
   holds the summary line), every top-level key must be documented in
   BENCH_SCHEMA.md (a backticked name), a ``*_error`` degradation key, or
   a summary-line field.

Run with no arguments for check 1 plus validation of every
``BENCH_*.json`` in the repo root; pass explicit JSON paths to validate
just those.  Exit code 0 = clean, 1 = drift (with a per-finding report).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
SCHEMA = os.path.join(REPO, "BENCH_SCHEMA.md")

#: summary-line fields (also the driver capture's `parsed` object) and
#: envelope keys of the driver capture files themselves
_SUMMARY_KEYS = {"metric", "value", "unit", "vs_baseline", "summary",
                 "backend", "lr_impl", "tpu_unavailable"}
_CAPTURE_ENVELOPE = {"n", "cmd", "rc", "tail", "parsed"}


def bench_metric_versions(src: str) -> dict:
    """Every ``<name>metric_version`` literal assigned in bench.py, from
    both the dict-literal and the subscript-assignment forms."""
    found = {}
    for pat in (r'"((?:\w+_)?metric_version)":\s*(\d+)',
                r'\["((?:\w+_)?metric_version)"\]\s*=\s*(\d+)'):
        for name, val in re.findall(pat, src):
            found[name] = int(val)
    return found


def schema_metric_versions(doc: str) -> dict:
    """The 'Metric versions' table: | `name` ... | value |"""
    section = doc.split("## Metric versions", 1)
    if len(section) < 2:
        return {}
    body = section[1].split("\n## ", 1)[0]
    found = {}
    for name, val in re.findall(r"\|\s*`(\w+)`[^|]*\|\s*(\d+)\s*\|", body):
        found[name] = int(val)
    return found


def schema_documented_keys(doc: str) -> set:
    """Every backticked identifier in BENCH_SCHEMA.md (the documented
    vocabulary; dotted names count for their leading segment too)."""
    keys = set()
    for name in re.findall(r"`([A-Za-z0-9_.*]+)`", doc):
        keys.add(name)
        keys.add(name.split(".", 1)[0])
    return keys


def check_versions() -> list:
    bench_v = bench_metric_versions(open(BENCH).read())
    schema_v = schema_metric_versions(open(SCHEMA).read())
    problems = []
    for name, val in sorted(bench_v.items()):
        if name not in schema_v:
            problems.append(
                f"bench.py emits {name}={val} but BENCH_SCHEMA.md's "
                "'Metric versions' table does not list it")
        elif schema_v[name] != val:
            problems.append(
                f"{name}: bench.py says {val}, BENCH_SCHEMA.md says "
                f"{schema_v[name]} — bump both together")
    for name in sorted(set(schema_v) - set(bench_v)):
        problems.append(
            f"BENCH_SCHEMA.md documents {name} but bench.py no longer "
            "emits it")
    return problems


def _validate_line(obj: dict, documented: set, origin: str) -> list:
    problems = []
    for key in obj:
        ok = (key in documented or key in _SUMMARY_KEYS
              or key == "notes" or key.endswith("_error"))
        if not ok:
            problems.append(
                f"{origin}: top-level key {key!r} is not documented in "
                "BENCH_SCHEMA.md")
    return problems


def check_json(path: str, documented: set) -> list:
    text = open(path).read().strip()
    try:
        whole = json.loads(text)
    except json.JSONDecodeError:
        whole = None
    if isinstance(whole, dict) and "parsed" in whole:
        # driver capture: envelope + truncated tail + parsed summary line
        # (parsed is null when the round produced no parseable line)
        problems = []
        for key in set(whole) - _CAPTURE_ENVELOPE:
            problems.append(
                f"{path}: unexpected capture-envelope key {key!r}")
        if isinstance(whole["parsed"], dict):
            problems += _validate_line(whole["parsed"], documented,
                                       f"{path}:parsed")
        return problems
    problems = []
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            problems.append(f"{path}:{i + 1}: not a JSON line")
            continue
        if isinstance(obj, dict):
            problems += _validate_line(obj, documented, f"{path}:{i + 1}")
    return problems


def main(argv) -> int:
    problems = check_versions()
    paths = argv or sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    documented = schema_documented_keys(open(SCHEMA).read())
    for path in paths:
        problems += check_json(path, documented)
    for p in problems:
        print(f"SCHEMA DRIFT: {p}")
    if not problems:
        print(f"bench schema clean ({len(paths)} json file(s) checked)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
