"""Does `device_put` overlap device compute through the axon tunnel?

VERDICT r4 weak #3: replay-epoch time is device_put-bound and nothing
overlaps the put.  The prefetch pipeline (data/prefetch.py) already
schedules puts from a separate thread, `depth` batches ahead — so if the
consumer still waits, either (a) the tunnel serializes transfer RPCs
with execute RPCs (a latency floor no host-side buffering can fix), or
(b) the put thread can't keep up but parallel puts would (fixable with
put workers).  This probe distinguishes them with three measurements on
the real chip:

1. `compute_s`     — N long jitted steps, nothing else.
2. `put_s`         — M device_puts of a batch-sized array, no compute.
3. `overlap_s`     — both interleaved: puts issued from a thread while
                     the N steps run.
4. `par_put_s`     — M puts issued from 4 threads concurrently.

Verdicts:
- overlap_s ~= max(compute_s, put_s)  -> puts DO overlap; a deeper
  on-device buffer helps; wire put parallelism into prefetch.
- overlap_s ~= compute_s + put_s      -> tunnel serializes; the replay
  floor is transport latency, record it and move on (VERDICT's
  "attributed measurement" branch).
- par_put_s << put_s                  -> parallel put RPCs pipeline;
  raise prefetch put concurrency.

Run (relay up): python scripts/put_overlap_probe.py
"""

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main() -> None:
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    batch = np.random.default_rng(0).normal(
        size=(1 << 14, 39)).astype(np.float32)   # bench-shaped batch
    n_steps, n_puts = 8, 8

    dim = 4096

    @jax.jit
    def heavy(x):
        # ~35 GFLOP of matmul chain: long enough (~0.2 ms x chain) that
        # an overlapping put has real compute to hide behind
        for _ in range(64):
            x = jnp.tanh(x @ w)
        return x

    w = jnp.asarray(np.random.default_rng(1).normal(
        size=(dim, dim)).astype(np.float32) / np.sqrt(dim))
    x0 = jnp.asarray(np.random.default_rng(2).normal(
        size=(256, dim)).astype(np.float32))
    np.asarray(heavy(x0)[0, :1])                  # compile + warm

    def run_compute():
        x = x0
        for _ in range(n_steps):
            x = heavy(x)
        np.asarray(x[0, :1])                      # completion fence

    def run_puts(k=n_puts, fence=True):
        outs = [jax.device_put(batch + np.float32(i)) for i in range(k)]
        if fence:
            for o in outs:
                np.asarray(o[0, :1])
        return outs

    run_puts(2)                                   # warm the transfer path

    t0 = time.perf_counter()
    run_compute()
    compute_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    run_puts()
    put_s = time.perf_counter() - t0

    # interleaved: puts from a thread (the prefetch topology) while the
    # same compute chain runs on the main thread
    t0 = time.perf_counter()
    th = threading.Thread(target=run_puts)
    th.start()
    run_compute()
    th.join()
    overlap_s = time.perf_counter() - t0

    # parallel puts: do concurrent transfer RPCs pipeline?
    t0 = time.perf_counter()
    threads = [threading.Thread(target=run_puts, args=(n_puts // 4,))
               for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    par_put_s = time.perf_counter() - t0

    serial = compute_s + put_s
    ideal = max(compute_s, put_s)
    verdict = ("overlaps" if overlap_s < serial * 0.75 else
               "serialized" if overlap_s > serial * 0.9 else "partial")
    print(json.dumps({
        "backend": backend,
        "compute_s": round(compute_s, 3),
        "put_s": round(put_s, 3),
        "overlap_s": round(overlap_s, 3),
        "parallel_put_s": round(par_put_s, 3),
        "serial_sum_s": round(serial, 3),
        "ideal_overlap_s": round(ideal, 3),
        "verdict": verdict,
        "parallel_puts_pipeline": par_put_s < put_s * 0.75,
    }))


if __name__ == "__main__":
    main()
