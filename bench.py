"""Headline benchmarks (BASELINE.md driver metrics), one JSON line.

Primary metric — the driver's first target — is **LogisticRegression
epochs/sec on a Criteo-shaped problem**: 13 dense + 26 hashed categorical
features in a 2^20-dim hash space, trained with the SAME mixed update the
framework's `sgd_fit_mixed` runs (dense slots via matvec, categorical via
128-lane blocked gather/scatter against the HBM-resident weight; the
generic `sgd_fit_sparse` (indices, values) path is reported as a
secondary).  Also reported in the same line:

- rows/sec, achieved TFLOP/s and MFU (fraction of v5e peak).  Sparse LR is
  HBM-bandwidth-bound, not MXU-bound — the MFU is honest and small; the
  achieved HBM GB/s in the notes is the number that tracks the roofline.
- kmeans_iterations_per_sec (the round-1 metric, unchanged methodology),
  preceded by an ON-DEVICE Pallas<->XLA parity assert: one fused-kernel
  stats update must match the XLA body's centroids before anything is
  timed — a miscompiling kernel fails the bench instead of shipping a fast
  wrong KMeans.
- notes.breakdown: fused-loop epoch time vs out-of-core (datacache +
  prefetch) epoch time — the compute vs ingest split that tells the next
  round where the bottleneck is.  The ingest leg self-calibrates: it times
  one host->device batch first and skips (with a note) if the tunnel would
  make the measurement meaningless.

The reference publishes no numbers (BASELINE.md); vs_baseline anchors are
driver-specified host-numpy loops (same algorithm, subsampled and scaled —
both kernels are exactly O(rows)).

Timing methodology (axon-tunnel gotchas, measured empirically in round 1):
- block_until_ready does not actually block through the tunnel; np.asarray
  (device_get) is the only reliable completion fence.
- every run call pays a fixed ~70 ms tunnel round-trip, so short scans
  understate the device rate badly; each timed call covers many epochs.
- repeated calls with identical args can be served from a relay-side cache;
  every timed trial uses distinct inputs.
- large host->device uploads are slow and device_put-with-sharding can
  embed the array into the compile RPC (HTTP 413) — so ALL benchmark data
  is generated ON DEVICE by jitted jax.random programs; only scalars cross
  the tunnel.
"""

import json
import os
import time

import numpy as np

# --- problem sizes (Criteo-shaped LR + round-1 KMeans) ---------------------
LR_ROWS = 1 << 20        # 1M rows resident in HBM for the fused loop
LR_DIM = 1 << 20         # hash-space size (2^20, the Criteo config)
LR_NNZ = 39              # 13 dense slots + 26 hashed categorical
LR_BATCH = 1 << 15       # 32 steps/epoch
LR_EPOCHS_PER_CALL = 8
N, D, K = 1_048_576, 64, 256
KM_ITERS = 480
HOST_SUBSAMPLE = 16
V5E_PEAK_FLOPS = 197e12  # bf16 peak; f32 work => MFU is conservative

# --- frozen host-baseline anchors (VERDICT r4 weak #1) ---------------------
# The same-run host-numpy denominators swung 2-6.6x across r4 runs on the
# phasing 1-core bench host while the device numerators held to three
# significant figures — the ratio column was noise.  From r5 the published
# vs_baseline ratios divide by these FROZEN anchors: each is the BEST
# (fastest) host sample recorded across the six r4 TPU runs, i.e. the most
# conservative ratio.  The live host rate is still measured every run and
# recorded in notes as host_*_live for drift tracking; a future host
# change re-pins these with a metric-version bump.
HOST_LR_EPOCHS_PER_SEC = 2.087    # r4 run-2 host sample (10.202/4.887)
HOST_KMEANS_ITERS_PER_SEC = 0.3174  # r4 run-5 host sample (630.1/1985)


def _smoke() -> bool:
    """Non-TPU backends run a scaled-down smoke pass (CI sanity only)."""
    import jax

    return jax.default_backend() != "tpu"


def _criteo_device_data(steps: int, batch: int, seed: int):
    """Synthetic Criteo-shaped rows generated ON DEVICE: 13 dense N(0,1)
    features, 26 hashed categorical indices int32 in [32, LR_DIM), labels
    driven by marker slots 16/17 so the problem is learnable.  Returns
    device arrays (dense, cat, y)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gen(key):
        kc, kd, ky = jax.random.split(key, 3)
        y = jax.random.bernoulli(ky, 0.5, (steps, batch)).astype(jnp.float32)
        cat = jax.random.randint(kc, (steps, batch, 26), 32, LR_DIM,
                                 jnp.int32)
        cat = cat.at[:, :, 0].set(jnp.where(y == 1, 16, 17))
        dense = jax.random.normal(kd, (steps, batch, 13), jnp.float32)
        return dense, cat, y

    return gen(jax.random.PRNGKey(seed))


def _as_sparse_pair(dense, cat):
    """(indices, values) encoding of the same rows for the generic path."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def enc(dense, cat):
        steps, batch, nd = dense.shape
        dense_idx = jnp.broadcast_to(
            jnp.arange(nd, dtype=jnp.int32), (steps, batch, nd))
        idx = jnp.concatenate([dense_idx, cat], axis=2)
        vals = jnp.concatenate(
            [dense, jnp.ones(cat.shape, jnp.float32)], axis=2)
        return idx, vals

    return enc(dense, cat)


def _criteo_host_data(rows: int, rng: np.random.Generator):
    """Host twin of :func:`_criteo_device_data` (same distribution) for the
    numpy baseline and the out-of-core cache.  Returns the (indices,
    values) encoding plus the (dense, cat) split."""
    dense = rng.normal(size=(rows, 13)).astype(np.float32)
    cat = rng.integers(32, LR_DIM, size=(rows, 26)).astype(np.int32)
    y = rng.integers(0, 2, size=rows).astype(np.float32)
    cat[:, 0] = np.where(y == 1, 16, 17)
    dense_idx = np.broadcast_to(np.arange(13, dtype=np.int32),
                                (rows, 13)).copy()
    idx = np.concatenate([dense_idx, cat], axis=1)
    vals = np.concatenate([dense, np.ones((rows, 26), np.float32)], axis=1)
    return idx, vals, y, dense, cat


def _host_lr_rate(batch: int, rng: np.random.Generator) -> float:
    """Host numpy epoch rate for the same mixed update, subsampled.
    Best of 3 trials: the shared host CPU's load varies run to run by
    2-4x, so a single trial makes vs_baseline noise, not signal."""
    sub = max(LR_ROWS // HOST_SUBSAMPLE, batch)
    _, _, y, dense, cat = _criteo_host_data(sub, rng)
    lr = 0.5
    best = float("inf")
    for _ in range(3):
        w = np.zeros(LR_DIM, np.float32)
        b = 0.0
        start = time.perf_counter()
        for s in range(0, sub, batch):
            db, cb, yb = dense[s:s + batch], cat[s:s + batch], y[s:s + batch]
            margin = db @ w[:13] + w[cb].sum(axis=1) + b
            p = 1.0 / (1.0 + np.exp(-np.clip(margin, -30, 30)))
            r = (p - yb) / len(yb)
            np.add.at(w, cb.reshape(-1), np.repeat(-lr * r, 26))
            w[:13] -= lr * (r @ db)
            b -= lr * r.sum()
        best = min(best, time.perf_counter() - start)
    return 1.0 / (best * (LR_ROWS / sub))


def bench_logreg(results: dict) -> None:
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import (
        SGDConfig, _mixed_update, _sparse_update)

    rows = LR_ROWS if not _smoke() else 1 << 14
    epochs = LR_EPOCHS_PER_CALL if not _smoke() else 2
    batch = LR_BATCH if not _smoke() else 1 << 12
    steps = rows // batch

    cfg = SGDConfig(learning_rate=0.5, tol=0)
    mixed_update = _mixed_update(logistic_loss, cfg)
    sparse_update = _sparse_update(logistic_loss, cfg)

    def make_runner(update, lead=2):
        # lead: how many of the two leading data tensors the update
        # reads — the r4 ELL updates take no raw index tensors (margins
        # and scatters both ride the layout), so their runners pass
        # only `dense` (mixed, lead=1) or neither (sparse, lead=0);
        # the unused tensors stay runner inputs so every leg shares the
        # same data residency.
        @jax.jit
        def run_epochs(params, wmul, a, b, y, *extra):
            # wmul perturbs the sample weights per trial: distinct inputs
            # defeat any relay-side result cache WITHOUT rebuilding the
            # (expensive) data + static ELL layout per trial
            ones = jnp.full(y.shape, 1.0 + wmul, jnp.float32)
            leads = (a, b)[:lead]

            def epoch(params, _):
                def step(params, i):
                    ex = tuple(e[i] for e in extra)
                    la = tuple(t[i] for t in leads)
                    return update(params, *la, *ex, y[i], ones[i])

                params, losses = jax.lax.scan(
                    step, params, jnp.arange(steps, dtype=jnp.int32))
                return params, jnp.mean(losses)

            return jax.lax.scan(epoch, params, jnp.arange(epochs))

        return run_epochs

    def fresh_params():
        return {"w": jnp.zeros((LR_DIM,), jnp.float32),
                "b": jnp.zeros((), jnp.float32)}

    def measure(run_epochs, data_args):
        from flink_ml_tpu.utils.profiler import fenced_call

        params, losses = run_epochs(fresh_params(), 0.0, *data_args)
        loss_host = np.asarray(losses)     # fence = device_get
        assert np.all(np.isfinite(loss_host))
        assert loss_host[-1] < loss_host[0], "LR bench did not learn"
        trials = []
        for t in range(1, 4):
            # fenced_call = THE shared timing idiom (utils/profiler.py):
            # probe-fetch of the loss log is the completion fence
            _, secs = fenced_call(run_epochs, fresh_params(), t * 1e-6,
                                  *data_args, probe_of=lambda r: r[1])
            trials.append(secs)
        return min(trials)

    # headline: the mixed dense+categorical path via EXACTLY what
    # sgd_fit_mixed plans — the ELL static-routing kernel on a single TPU
    # device (ops/ell_scatter.py), the XLA scatter elsewhere.  Before any
    # timing, one full epoch of the kernel path must match the XLA
    # oracle's weights on device (same stance as the KMeans kernel
    # parity assert below): a miscompiling kernel fails the bench.
    from flink_ml_tpu.models.common.sgd import (
        _mixed_update_ell, plan_mixed_impl)
    from flink_ml_tpu.parallel.mesh import default_mesh

    impl = plan_mixed_impl(LR_DIM, default_mesh(), steps)
    results["notes"]["lr_impl"] = impl

    def device_layout(cat):
        from flink_ml_tpu.ops.ell_scatter import ell_layout_device

        # ovf_cap sized for the post-heavy residual: with the marker
        # feature routed to the heavy path, spill is the Poisson tail;
        # assert_capacities turns an undersized cap into a named error
        # instead of a parity-assert failure downstream
        lay = ell_layout_device(
            cat, LR_DIM, ovf_cap=1 << 13).assert_capacities().trim_overflow()
        return (lay.src, lay.pos, lay.mask, lay.ovf_idx, lay.ovf_src,
                lay.heavy_idx, lay.heavy_cnt)

    mixed_args = _criteo_device_data(steps, batch, seed=0)
    mixed_ell_ok = False
    run_oracle = None
    if impl == "ell":
        # any kernel-path failure (parity divergence, Mosaic compile
        # quirk on a different toolchain) degrades to the XLA path with
        # a note — a broken fast path must not cost the round its bench
        try:
            ell_update = _mixed_update_ell(logistic_loss, cfg)
            run_oracle = make_runner(mixed_update)
            run_ell = make_runner(ell_update, lead=1)

            dense0, cat0, y0 = mixed_args
            extra0 = device_layout(cat0)
            p_ell, _ = run_ell(fresh_params(), 0.0, dense0, cat0, y0,
                               *extra0)
            p_ora, _ = run_oracle(fresh_params(), 0.0, dense0, cat0, y0)
            w_ell, w_ora = np.asarray(p_ell["w"]), np.asarray(p_ora["w"])
            if not np.allclose(w_ell, w_ora, rtol=1e-3, atol=1e-4):
                raise AssertionError(
                    "ELL kernel path diverged from the XLA oracle after "
                    f"{epochs} epochs: max abs diff "
                    f"{np.max(np.abs(w_ell - w_ora))}")
            results["ell_xla_allclose"] = True
            mixed_ell_ok = True
        except Exception as exc:   # noqa: BLE001 — degrade, don't die
            results["notes"]["lr_impl"] = "xla (ell failed)"
            results["notes"]["lr_ell_error"] = repr(exc)[:300]
    if mixed_ell_ok:
        best = measure(run_ell, mixed_args + extra0)
    else:
        # reuse the already-compiled oracle when the try got that far
        best = measure(run_oracle or make_runner(mixed_update), mixed_args)
    epoch_s = best / epochs
    results["logreg_epochs_per_sec"] = round(epochs / best, 3)
    results["rows_per_sec"] = round(rows / epoch_s, 1)

    # secondary: the generic (indices, values) sparse path on the same
    # rows — also through the planned ELL path on TPU (values-aware
    # layout), with the same pre-timing oracle parity stance
    idx0, vals0 = _as_sparse_pair(mixed_args[0], mixed_args[1])
    sparse_args = (idx0, vals0, mixed_args[2])

    # the sparse ELL leg is independent of the mixed one: a mixed-leg
    # failure does not skip it, and its impl is tagged either way
    sparse_ok = False
    run_sparse_oracle = None
    if impl == "ell":
        try:
            from flink_ml_tpu.models.common.sgd import _sparse_update_ell
            from flink_ml_tpu.ops.ell_scatter import ell_layout_device

            # heavy_cap: the pair encoding makes EVERY dense slot index
            # (0..12, batch occurrences each) heavy, plus label markers
            lay = ell_layout_device(
                idx0, LR_DIM, ovf_cap=1 << 13, heavy_cap=24,
                values=vals0).assert_capacities().trim_overflow()
            sparse_args_ell = sparse_args + (
                lay.src, lay.pos, lay.mask, lay.val, lay.ovf_idx,
                lay.ovf_src, lay.ovf_val, lay.heavy_idx, lay.heavy_cnt)
            run_sparse_ell = make_runner(
                _sparse_update_ell(logistic_loss, cfg), lead=0)
            p_se, _ = run_sparse_ell(fresh_params(), 0.0,
                                     *sparse_args_ell)
            run_sparse_oracle = make_runner(sparse_update)
            p_so, _ = run_sparse_oracle(fresh_params(), 0.0, *sparse_args)
            if not np.allclose(np.asarray(p_se["w"]),
                               np.asarray(p_so["w"]),
                               rtol=1e-3, atol=1e-4):
                raise AssertionError(
                    "sparse ELL path diverged from oracle")
            sparse_ok = True
        except Exception as exc:   # noqa: BLE001 — degrade, don't die
            results["notes"]["lr_sparse_ell_error"] = repr(exc)[:300]
    results["notes"]["lr_sparse_impl"] = "ell" if sparse_ok else "xla"
    if sparse_ok:
        best_sparse = measure(run_sparse_ell, sparse_args_ell)
    else:
        best_sparse = measure(run_sparse_oracle or
                              make_runner(sparse_update), sparse_args)
    results["logreg_sparse_epochs_per_sec"] = round(epochs / best_sparse, 3)

    # arithmetic: per row ~2*2*NNZ flops (score + grad MACs); the blocked
    # scatter/gather move 128-lane rows, so the byte roofline counts rows
    flops_per_epoch = rows * 4 * LR_NNZ
    tflops = flops_per_epoch / epoch_s / 1e12
    results["tflops"] = round(tflops, 4)
    results["mfu"] = round(tflops * 1e12 / V5E_PEAK_FLOPS, 6)
    # roofline: per epoch the 26 cat slots each gather+scatter a 128-lane
    # f32 row (read+RMW ~3 passes) plus the streamed (dense, cat, label)
    bytes_per_epoch = (rows * (13 * 4 + 26 * 4 + 4)
                       + rows * 26 * 128 * 4 * 3)
    results["lr_hbm_gbps"] = round(bytes_per_epoch / epoch_s / 1e9, 1)

    host_rate = _host_lr_rate(batch, np.random.default_rng(1))
    results["vs_baseline"] = round(results["logreg_epochs_per_sec"]
                                   / HOST_LR_EPOCHS_PER_SEC, 3)
    results.setdefault("notes", {})["lr"] = {
        "rows": rows, "dim": LR_DIM, "nnz": LR_NNZ, "batch": batch,
        "layout": "mixed: 13 dense slots (matvec) + 26 hashed categorical "
                  "(128-lane blocked gather/scatter)",
        "bound": "per-row random-access op rate on the categorical slots",
        "host_epochs_per_sec_anchor": HOST_LR_EPOCHS_PER_SEC,
        "host_epochs_per_sec_live": round(host_rate, 6),
        # metric redefinition marker: r1/early-r2 measured the generic
        # (indices, values) sparse kernel under this key; from r2-final the
        # headline is the mixed layout (the framework's fastest Criteo
        # path) and logreg_sparse_epochs_per_sec carries the old series;
        # v3 (r5): vs_baseline divides by the FROZEN host anchor (see
        # HOST_LR_EPOCHS_PER_SEC) instead of the noisy same-run sample
        "metric_version": 3,
    }


def _auto_workers() -> int:
    return max(1, min(8, (os.cpu_count() or 1) - 1))


def bench_logreg_outofcore(results: dict) -> None:
    """Ingest path: the same MIXED-layout LR update fed from the datacache
    through prefetch_to_device — epoch time here minus the fused epoch
    time is the infeed cost.  Since r3 the layout matches the fused
    headline (dense+indices, VERDICT r2 weak #6 fixed —
    outofcore_metric_version 2) and the prefetch pipeline reports an
    attributed breakdown (host read / decode / device_put / device wait)
    so tunnel artifact is separable from ingest design.  On a tunneled
    chip the host->device leg can dominate by orders of magnitude; a
    one-batch calibration skips the fit (with a note) when a full epoch
    would exceed the time budget."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.data.prefetch import PrefetchStats
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    rows = (1 << 18) if not _smoke() else 1 << 14
    batch = (1 << 14) if not _smoke() else 1 << 12
    rng = np.random.default_rng(7)
    _, _, y, dense, cat = _criteo_host_data(rows, rng)

    workers = _auto_workers()
    tmp = tempfile.mkdtemp(prefix="bench_lr_cache_")
    cache = os.path.join(tmp, "cache")
    writer = DataCacheWriter(cache, segment_rows=1 << 16,
                             workers=min(4, workers))
    chunk = 1 << 15
    t0 = time.perf_counter()
    for s in range(0, rows, chunk):
        writer.append({"features_dense": dense[s:s + chunk],
                       "features_indices": cat[s:s + chunk],
                       "label": y[s:s + chunk]})
    writer.finish()
    write_s = time.perf_counter() - t0
    cache_bytes = dense.nbytes + cat.nbytes + y.nbytes
    notes = results["notes"]["breakdown"] = {
        "cache_write_mb_per_sec": round(cache_bytes / write_s / 1e6, 1),
        "cache_write_workers": min(4, workers),
        "host_cores": os.cpu_count() or 1,
        # v3 (r4): 3 epochs with the decoded replay cache engaged — the
        # per-epoch average now mixes one record epoch with two replay
        # epochs (v2 averaged two identical decode-every-epoch passes)
        # v4 (r6): the fit runs chunked dispatch (steps_per_dispatch=8
        # default) — epoch times amortize the per-dispatch round-trip
        # 8x, and put/wait attribution is per-CHUNK (~1/8 the puts), so
        # v3-and-earlier per-batch numbers are not comparable.  The
        # put_workers=4 A/B deliberately pins steps_per_dispatch=1 to
        # keep measuring per-batch put parallelism.
        "outofcore_metric_version": 4,
    }

    # raw-TSV leg of the north-star ingest: Criteo parser MB/s (host-only
    # measurement, one pass over synthesized real-shape lines).  The
    # implementation tag matters: the pure-Python fallback is ~50-100x
    # slower, so an untagged number would silently corrupt the series on
    # a host without the native toolchain.
    from flink_ml_tpu.data import criteo
    from flink_ml_tpu.data.criteo import parse_chunk

    tsv_rows = (1 << 16) if not _smoke() else 1 << 12
    tsv = _synth_tsv(tsv_rows, np.random.default_rng(11))
    t0 = time.perf_counter()
    _, _, parsed_labels, consumed = parse_chunk(tsv, tsv_rows, LR_DIM - 13)
    parse_s = time.perf_counter() - t0
    assert len(parsed_labels) == tsv_rows and consumed == len(tsv)
    impl = "native" if criteo._native_lib() is not None else "python-fallback"
    notes["tsv_parse_mb_per_sec"] = round(len(tsv) / parse_s / 1e6, 1)
    notes["tsv_parse_impl"] = impl

    # calibrate: one batch upload + fenced step
    t0 = time.perf_counter()
    one = jnp.asarray(cat[:batch])
    np.asarray(one[0, :1])
    per_batch_s = time.perf_counter() - t0
    n_batches = rows // batch
    projected = per_batch_s * n_batches * 2.5  # dense+cat+label, margin
    if projected > 120:
        notes["outofcore"] = (
            f"skipped: ~{per_batch_s:.2f}s per {batch}-row batch upload "
            f"through the tunnel projects {projected:.0f}s/epoch — the "
            "measurement would time the tunnel, not the ingest design")
        return

    cfg = SGDConfig(learning_rate=0.5, max_epochs=3, tol=0)
    stats = PrefetchStats()
    stream_info: dict = {}
    t0 = time.perf_counter()
    sgd_fit_outofcore(
        logistic_loss, lambda: DataCacheReader(cache, batch_rows=batch),
        num_features=LR_DIM, config=cfg,
        dense_key="features_dense", indices_key="features_indices",
        prefetch_workers=workers, prefetch_stats=stats,
        stream_info=stream_info)
    ooc_epoch_s = (time.perf_counter() - t0) / cfg.max_epochs

    # put-parallelism A/B (r5, VERDICT r4 weak #3): the same 2-epoch fit
    # with 4 put workers — if the tunnel pipelines concurrent transfer
    # RPCs (scripts/put_overlap_probe.py), put_ms/infeed_gap_ms shrink
    # here same-run; if serialized, the pair documents the latency floor
    stats_pw = PrefetchStats()
    t0 = time.perf_counter()
    sgd_fit_outofcore(
        logistic_loss, lambda: DataCacheReader(cache, batch_rows=batch),
        num_features=LR_DIM,
        config=SGDConfig(learning_rate=0.5, max_epochs=2, tol=0),
        dense_key="features_dense", indices_key="features_indices",
        prefetch_workers=workers, prefetch_put_workers=4,
        # per-batch dispatch keeps this leg measuring PUT parallelism:
        # chunked puts would collapse it to ~2 transfers/epoch
        steps_per_dispatch=1,
        prefetch_stats=stats_pw, cache_decoded=False)
    pw_wall_s = time.perf_counter() - t0
    pw = {k: round(v / 2 * 1000, 1)
          for k, v in stats_pw.as_dict().items()
          if k not in ("batches", "chunks")}
    notes["outofcore_put_workers4"] = {
        "epoch_s": round(pw_wall_s / 2, 2),
        "device_put_ms_per_epoch": pw["put_s"],
        "infeed_gap_ms_per_epoch": pw["consumer_wait_s"],
    }

    # chunked-dispatch A/B (this PR): W=1 (one jit dispatch per batch)
    # vs the default W=8 scan under otherwise-identical settings
    # (cache_decoded off so every epoch pays the same decode).  The
    # headline is the closed fraction of the fused-vs-out-of-core gap —
    # how much of the per-batch-dispatch overhead the chunked scan
    # recovers.
    # A W=8 chunk pads short epochs to 8 steps (dead steps compute and
    # discard — the price of one compiled program for every chunk), so
    # the A/B needs >= 2 full chunks per epoch to measure amortization
    # rather than padding waste: the smoke shape's 4-batch epoch is
    # degenerate, so size the A/B's batch for 16 batches/epoch.
    ab_batch = batch if rows // batch >= 16 else rows // 16
    n_batches_ab = -(-rows // ab_batch)
    chunk_ab = {}
    for w_steps in (1, 8):
        si_w: dict = {}
        sgd_fit_outofcore(
            logistic_loss,
            lambda: DataCacheReader(cache, batch_rows=ab_batch),
            num_features=LR_DIM,
            config=SGDConfig(learning_rate=0.5, max_epochs=2, tol=0),
            dense_key="features_dense", indices_key="features_indices",
            prefetch_workers=workers, steps_per_dispatch=w_steps,
            cache_decoded=False, stream_info=si_w)
        # epoch 0 pays each W's one-time scan-program compile; the LAST
        # epoch is the steady state the amortization claim is about
        chunk_ab[w_steps] = {
            "epoch_s": si_w["epoch_seconds"][-1],
            "dispatches": si_w["dispatches_per_epoch"][-1],
        }
    w1_s, w8_s = chunk_ab[1]["epoch_s"], chunk_ab[8]["epoch_s"]

    # shuffled + block-keyed decode cache (r4): per-epoch reshuffle with
    # decode amortization — epoch 2 serves every block's decoded layout
    # from RAM in a fresh permutation
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    si2: dict = {}
    t0 = time.perf_counter()
    sgd_fit_outofcore(
        logistic_loss,
        lambda epoch: ShuffledCacheReader(cache, batch_rows=batch,
                                          seed=11, epoch=epoch),
        num_features=LR_DIM,
        config=SGDConfig(learning_rate=0.5, max_epochs=2, tol=0),
        dense_key="features_dense", indices_key="features_indices",
        prefetch_workers=workers, stream_info=si2)
    shuffled_s = time.perf_counter() - t0
    notes["outofcore_shuffled_block_cache"] = {
        "mode": si2.get("decoded_cache_mode"),
        "cached_batches": si2.get("decoded_cache_batches"),
        "epoch_s": si2.get("epoch_seconds"),
        "wall_s": round(shuffled_s, 2),
    }

    fused_epoch_s = (rows / results["rows_per_sec"]
                     if "rows_per_sec" in results else float("nan"))
    # chunked-dispatch breakdown: dispatch reduction at the default W=8
    # and the fraction of the fused-vs-out-of-core gap the scan closed.
    # The fraction is only meaningful when the A/B ran at the SAME batch
    # size the fused leg was timed at — in smoke mode ab_batch shrinks to
    # get 16 steps/epoch while fused_epoch_s derives from the fused run's
    # own batch size, and dividing those conflates step-count scaling
    # with per-dispatch overhead, so it reports None there.
    gap = w1_s - fused_epoch_s
    notes["outofcore_chunked"] = {
        "steps_per_dispatch": stream_info.get("steps_per_dispatch"),
        "dispatches_per_epoch": stream_info.get("dispatches_per_epoch"),
        "ab_batches_per_epoch": n_batches_ab,
        "dispatch_reduction_at_w8": round(
            n_batches_ab / chunk_ab[8]["dispatches"], 2),
        "w1_epoch_ms": round(1000 * w1_s, 1),
        "w8_epoch_ms": round(1000 * w8_s, 1),
        "gap_closed_fraction": (round((w1_s - w8_s) / gap, 3)
                                if ab_batch == batch
                                and np.isfinite(gap) and gap > 0
                                else None),
    }
    per_epoch = {k: round(v / cfg.max_epochs * 1000, 1)
                 for k, v in stats.as_dict().items()
                 if k not in ("batches", "chunks")}
    # r4 decoded replay cache: epoch 0 decodes + records, epochs 1+ replay
    # from RAM — the steady-state multi-epoch rate is the REPLAY rate
    ep_s = stream_info.get("epoch_seconds", [])
    replay_s = (sum(ep_s[1:]) / (len(ep_s) - 1)) if len(ep_s) > 1 else None
    notes.update({
        "lr_fused_epoch_ms_at_this_size": round(1000 * fused_epoch_s, 1),
        "lr_outofcore_epoch_ms": round(1000 * ooc_epoch_s, 1),
        "infeed_overhead_ms": round(1000 * (ooc_epoch_s - fused_epoch_s), 1),
        "outofcore_rows_per_sec": round(rows / ooc_epoch_s, 1),
        "outofcore_decoded_replay": {
            "cached_batches": stream_info.get("decoded_cache_batches", 0),
            "cached_mb": round(
                stream_info.get("decoded_cache_bytes", 0) / 1e6, 1),
            "record_epoch_ms": (round(1000 * ep_s[0], 1) if ep_s else None),
            "replay_epoch_ms": (round(1000 * replay_s, 1)
                                if replay_s is not None else None),
        },
        "outofcore_replay_rows_per_sec": (
            round(rows / replay_s, 1) if replay_s else None),
        # per-epoch attribution: host read / decode / device_put / the
        # time the CONSUMER waited on the queue (infeed gap).  On the
        # tunnel, put_ms dominating proves the residual is transport, not
        # ingest design.
        "outofcore_stage_ms_per_epoch": {
            "host_read_ms": per_epoch["read_s"],
            "host_decode_ms": per_epoch["transform_s"],
            "device_put_ms": per_epoch["put_s"],
            "infeed_gap_ms": per_epoch["consumer_wait_s"],
        },
        "prefetch_workers": workers,
    })


#: bump with ANY _synth_tsv format/content change — the e2e leg's cached
#: day-file is keyed on it (a same-width content change preserves size)
_SYNTH_TSV_VERSION = 1


def _synth_tsv(rows: int, rng: np.random.Generator) -> bytes:
    ints = rng.integers(0, 1000, size=(rows, 13))
    toks = rng.integers(0, 1 << 32, size=(rows, 26))
    return b"".join(
        b"%d\t%s\t%s\n" % (
            i & 1,
            b"\t".join(b"%d" % v for v in ints[i]),
            b"\t".join(b"%08x" % v for v in toks[i]))
        for i in range(rows))


def bench_criteo_e2e(results: dict) -> None:
    """The north-star pipeline measured as ONE wall clock: raw day-file
    TSV -> CriteoTSVReader (range-sharded parse) -> DataCacheWriter
    (segment-parallel) -> sgd_fit_outofcore(mixed=True) for one epoch,
    with per-stage rates.  The day-file is synthesized from a template
    block repeated to size (parse cost is line-shape-dependent, not
    content-dependent).  The train leg degrades to a row subset when the
    tunnel calibration projects it over budget — the ingest stages always
    run at full size."""
    import tempfile

    import jax.numpy as jnp

    from flink_ml_tpu.data.criteo import CriteoTSVReader
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.data.prefetch import PrefetchStats
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    target_rows = 10_000_000 if not _smoke() else 1 << 14
    template_rows = (1 << 17) if not _smoke() else 1 << 12
    reps = max(1, -(-target_rows // template_rows))
    rows = template_rows * reps
    workers = _auto_workers()
    notes = results["notes"]["criteo_e2e"] = {
        "rows": rows, "parse_workers": workers,
        "host_cores": os.cpu_count() or 1,
    }

    tmp = tempfile.mkdtemp(prefix="bench_criteo_e2e_")
    # 1-second disk microprobe (VERDICT r4 weak #2): the bench disk
    # phases 26-663 MB/s across runs, so every run records its own
    # disk phase to make residual e2e swings attributable
    probe_path = os.path.join(tmp, "disk_probe")
    probe_block = b"\0" * (8 << 20)
    t0 = time.perf_counter()
    probe_mb = 0
    with open(probe_path, "wb") as f:
        while time.perf_counter() - t0 < 1.0:
            f.write(probe_block)
            probe_mb += 8
        f.flush()
        os.fsync(f.fileno())
    notes["disk_probe_mb_per_sec"] = round(
        probe_mb / (time.perf_counter() - t0), 1)
    os.unlink(probe_path)

    # the seeded day-file is CACHED across runs (VERDICT r4 weak #2: run 6
    # spent 355 s writing its own synthetic input on a slow disk phase —
    # more than it charged to e2e); content is deterministic in
    # (seed, rows), so a size-matched cached file is the same file
    template = _synth_tsv(template_rows, np.random.default_rng(23))
    cache_dir = os.environ.get("BENCH_CACHE_DIR",
                               "/tmp/flink_ml_tpu_bench_cache")
    os.makedirs(cache_dir, exist_ok=True)
    # filename carries a content version (bump _SYNTH_TSV_VERSION with
    # any _synth_tsv format change) and reuse re-checks the first
    # template-block bytes — size alone cannot catch a same-width
    # content change
    day = os.path.join(cache_dir,
                       f"day_s23_v{_SYNTH_TSV_VERSION}_r{rows}.tsv")
    tsv_bytes = len(template) * reps

    def _prefix_matches() -> bool:
        with open(day, "rb") as f:
            return f.read(min(len(template), 1 << 20)) == \
                template[: 1 << 20]

    if (os.path.exists(day) and os.path.getsize(day) == tsv_bytes
            and _prefix_matches()):
        notes["synth_write_s"] = 0.0
        notes["synth_day_file"] = "cached"
    else:
        t0 = time.perf_counter()
        with open(day + ".part", "wb") as f:
            for _ in range(reps):
                f.write(template)
        os.replace(day + ".part", day)
        notes["synth_write_s"] = round(time.perf_counter() - t0, 1)
        notes["synth_day_file"] = "written"

    # stage 1+2: parse + cache as one pipeline (reader feeds writer)
    batch = 1 << 16
    cache = os.path.join(tmp, "cache")
    hash_space = LR_DIM - 13
    reader = CriteoTSVReader(day, batch_rows=batch, hash_space=hash_space,
                             workers=workers)
    # borrow_batches: CriteoTSVReader yields fresh arrays, so the
    # parallel writer can skip its defensive copies
    writer = DataCacheWriter(cache, segment_rows=1 << 20,
                             workers=min(4, workers),
                             borrow_batches=True)
    t0 = time.perf_counter()
    n_ingested = 0
    for b in reader:
        writer.append(b)
        n_ingested += len(b["label"])
    writer.finish()
    ingest_s = time.perf_counter() - t0
    assert n_ingested == rows, (n_ingested, rows)
    notes["ingest_rows_per_sec"] = round(rows / ingest_s, 1)
    notes["ingest_mb_per_sec"] = round(tsv_bytes / ingest_s / 1e6, 1)
    results["criteo_ingest_rows_per_sec"] = notes["ingest_rows_per_sec"]

    # stage 3: training epochs from the cache (tunnel-calibrated).
    # Two epochs, not one (VERDICT r3 task 6): the second epoch exercises
    # the per-epoch cache re-read + prefetch machinery that a single
    # pass never touches, and the per-row rate below is per epoch-row.
    train_epochs = 2
    t0 = time.perf_counter()
    one = jnp.asarray(np.zeros((1 << 14, 26), np.int32))
    np.asarray(one[0, :1])
    per_batch_s = time.perf_counter() - t0
    train_rows = rows
    projected = per_batch_s * (rows / (1 << 14)) * 2.5 * train_epochs
    # budget raised 150 -> 420 s in r5 (VERDICT r4 missing #2): the run-6
    # calibration put the FULL 10M-row 2-epoch leg at ~268 s through the
    # tunnel, so the complete measurement fits the budget and the north-
    # star number stops being a projection.  The subset fallback remains
    # for genuinely slow tunnel phases.
    if projected > 420:
        train_rows = min(rows, 1 << 18)
        notes["train_leg"] = (
            f"subset of {train_rows} rows: calibration projects "
            f"{projected:.0f}s for {train_epochs} epochs through the "
            "tunnel")
    notes["train_epochs"] = train_epochs

    cfg = SGDConfig(learning_rate=0.5, max_epochs=train_epochs, tol=0)
    stats = PrefetchStats()
    si: dict = {}

    def make_reader():
        r = DataCacheReader(cache, batch_rows=1 << 14)
        if train_rows < rows:
            # bound the epoch: wrap to stop after train_rows
            def limited():
                seen = 0
                for b in r:
                    if seen >= train_rows:
                        return
                    yield b
                    seen += len(b["label"])
            return limited()
        return r

    t0 = time.perf_counter()
    sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=LR_DIM, config=cfg,
        dense_key="features_dense", indices_key="features_indices",
        prefetch_workers=workers, prefetch_stats=stats,
        # caching OFF here: the e2e metric's train leg is defined (r2/r3)
        # as decode-every-epoch so the series stays comparable, and the
        # second epoch exists precisely to exercise the per-epoch cache
        # re-read path.  The decoded-replay win is measured by the
        # dedicated out-of-core leg (outofcore_metric_version 3).
        cache_decoded=False, stream_info=si)
    train_s = time.perf_counter() - t0
    notes["train_rows_per_sec"] = round(
        train_rows * train_epochs / train_s, 1)   # per epoch-row
    notes["train_stage_s"] = stats.as_dict()
    notes["train_epoch_s"] = si.get("epoch_seconds")
    notes["train_decoded_replay_batches"] = si.get(
        "decoded_cache_batches", 0)

    # the e2e metric: full-pipeline rows/sec over the stages all run at
    # the same size; when the train leg was truncated, scale its cost to
    # full size for the combined figure and say so.  Train cost is
    # normalised to ONE full-size epoch so the metric's definition is
    # unchanged from r2/r3.
    train_full_s = train_s * (rows / train_rows) / train_epochs
    notes["e2e_wall_s"] = round(ingest_s + train_full_s, 1)
    if train_rows < rows:
        notes["e2e_wall_s_note"] = "train leg scaled from subset"
    results["criteo_e2e_rows_per_sec"] = round(
        rows / (ingest_s + train_full_s), 1)

    # cache-ON series (VERDICT r4 missing #2): the SAME train leg with
    # the decoded replay cache engaged — epoch 0 decodes + records,
    # epoch 1 replays from RAM.  Reported next to the comparable
    # cache-OFF series above, never mixed into it.
    stats_c = PrefetchStats()
    si_c: dict = {}
    t0 = time.perf_counter()
    sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=LR_DIM, config=cfg,
        dense_key="features_dense", indices_key="features_indices",
        prefetch_workers=workers, prefetch_stats=stats_c,
        cache_decoded=True, stream_info=si_c)
    train_cached_s = time.perf_counter() - t0
    cached_full_s = train_cached_s * (rows / train_rows) / train_epochs
    notes["train_cached"] = {
        "wall_s": round(train_cached_s, 1),
        "epoch_s": si_c.get("epoch_seconds"),
        "cached_batches": si_c.get("decoded_cache_batches", 0),
        "rows_per_sec": round(train_rows * train_epochs / train_cached_s,
                              1),
    }
    results["criteo_e2e_cached_rows_per_sec"] = round(
        rows / (ingest_s + cached_full_s), 1)


def _host_kmeans_rate(points: np.ndarray, centroids: np.ndarray,
                      n: int) -> float:
    """Best of 3 trials (see _host_lr_rate: shared-CPU noise)."""
    sub = points[: max(n // HOST_SUBSAMPLE, K)]
    reps = 2
    best = float("inf")
    for _ in range(3):
        c = centroids.copy()
        start = time.perf_counter()
        for _ in range(reps):
            cross = sub @ c.T
            d2 = ((sub * sub).sum(1)[:, None] - 2 * cross
                  + (c * c).sum(1)[None, :])
            assign = d2.argmin(1)
            sums = np.zeros_like(c)
            np.add.at(sums, assign, sub)
            counts = np.bincount(assign, minlength=K).astype(np.float32)
            nonzero = counts > 0
            c[nonzero] = sums[nonzero] / counts[nonzero, None]
        best = min(best, time.perf_counter() - start)
    return 1.0 / ((best / reps) * (n / len(sub)))


def bench_kmeans(results: dict) -> None:
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering import kmeans as km

    n = N if not _smoke() else 1 << 14
    iters = KM_ITERS if not _smoke() else 8
    # points generated ON DEVICE (no 256MB tunnel upload); the host baseline
    # uses a small statistically-identical numpy draw
    points = jax.jit(
        lambda key: jax.random.normal(key, (n, D), jnp.float32))(
            jax.random.PRNGKey(0))
    mask = jnp.ones((n,), jnp.float32)
    init = points[:K] + 0.0

    measure = DistanceMeasure.get_instance("euclidean")
    mesh = km.default_mesh()
    impl, block_n = km._plan_fit_impl(n, D, K, measure, mesh)
    xla_body = km.kmeans_epoch_step(measure, K)
    if impl == "pallas":
        # EXACTLY what KMeans.fit plans: tie_policy comes from the
        # estimator's default (KMeansParams.TIE_POLICY — "first" since
        # r4: the reference's argmin semantics, ties included, per
        # ADVICE r3).  It must agree with the XLA body up to f32
        # reduction order — asserted on device before timing.
        tie = km.KMeans().get_tie_policy()
        body = km.kmeans_epoch_step_pallas(K, block_n=block_n,
                                           tie_policy=tie)
    else:  # non-TPU backend fallback: the XLA body
        body = xla_body

    # ---- Pallas <-> XLA parity on device (VERDICT r1 task 6) ----
    # points/mask ride as jit ARGUMENTS: a closed-over device array would
    # be embedded as a constant in the compile RPC (HTTP 413 at 256 MB
    # through the tunnel)
    c_bench = np.asarray(jax.jit(
        lambda c, pts, m: body(c, 0, (pts, m)).feedback)(init, points, mask))
    c_xla = np.asarray(jax.jit(
        lambda c, pts, m: xla_body(c, 0, (pts, m)).feedback)(
            init, points, mask))
    # Tolerance scale: the kernel computes distances in a different f32
    # op order than the XLA body, so a near-equidistant point can flip its
    # argmin — one flipped point among n/K ~ 4096 shifts that centroid by
    # ~|x-c|/4096 ~ 1e-3.  A handful of flips is methodology noise; a
    # miscompile shows up at O(0.1+).
    if not np.allclose(c_bench, c_xla, rtol=5e-3, atol=5e-3):
        raise AssertionError(
            "Pallas kernel diverged from XLA body on device: max abs diff "
            f"{np.max(np.abs(c_bench - c_xla))}")
    results["pallas_xla_allclose"] = True
    results["notes"]["kmeans_impl"] = f"{impl}(block_n={block_n})"

    @jax.jit
    def run_iters(centroids, points, mask):
        def scan_step(c, epoch):
            return body(c, epoch, (points, mask)).feedback, None

        final, _ = jax.lax.scan(scan_step, centroids,
                                jnp.arange(iters, dtype=jnp.int32))
        return final

    from flink_ml_tpu.utils.profiler import fenced_call

    np.asarray(run_iters(init, points, mask))  # compile + warmup
    trials = []
    for trial in range(1, 4):
        trial_init = points[K * trial:K * (trial + 1)] + 0.0
        _, secs = fenced_call(run_iters, trial_init, points, mask)
        trials.append(secs)
    tpu_rate = iters / min(trials)

    host_rng = np.random.default_rng(0)
    host_points = host_rng.normal(
        size=(max(n // HOST_SUBSAMPLE, 2 * K), D)).astype(np.float32)
    host_rate = _host_kmeans_rate(host_points, host_points[:K].copy(), n)
    results["kmeans_iterations_per_sec"] = round(tpu_rate, 3)
    results["kmeans_vs_baseline"] = round(
        tpu_rate / HOST_KMEANS_ITERS_PER_SEC, 3)
    results["notes"]["kmeans_host_rate_live"] = round(host_rate, 5)
    # metric_version history for the kmeans series: v1 (r1) = single-trial
    # host baseline; v2 (r2) = best-of-3 host baseline (the r1->r2
    # kmeans_vs_baseline cliff is that redefinition, not a regression);
    # v3 (r3) = device rate is the KMeans.fit-planned kernel config
    # (tiePolicy param default), measured methodology otherwise unchanged;
    # v4 (r4, never benched) = tiePolicy default flipped to "split";
    # v5 (r4) = default becomes "first" (exact reference argmin tie
    # semantics, ADVICE r3 medium) — fit-planned path still what's
    # timed; slightly more work per iteration than v3's "fast";
    # v6 (r5) = kmeans_vs_baseline divides by the FROZEN host anchor
    # (HOST_KMEANS_ITERS_PER_SEC — the 6.6x r4 ratio swing was all
    # denominator); the live host sample moves to notes.
    results["notes"]["kmeans_metric_version"] = 6
    # assign+reduce are two (n, K, D)-scale matmuls: ~4*n*K*D flops/iter
    results["notes"]["kmeans_tflops"] = round(
        4 * n * K * D * tpu_rate / 1e12, 1)


def bench_workset(results: dict) -> None:
    """Workset-iteration leg (workset_metric_version 1): bound-filtered
    KMeans vs the BSP fit on the same clustered dataset, A/B in one run.

    Reports rounds-to-converge (the while_loop exit vs the BSP loop's
    fixed maxIter), the active-fraction decay curve (how fast the Hamerly
    bounds settle the points), and assign-FLOPs-actually-spent vs BSP —
    the bound-filter accounting: points scored per round x the per-point
    assign cost, vs every-point-every-round.  The fused program still
    scores densely (fixed shapes), so the FLOPs ratio is the sum of the
    early-exit saving (real wall-clock today) and the bound-filter saving
    (what a compacting backend banks); both components are in the notes.

    Headline fields are PRE-NULLED at entry: a mid-leg failure (or a
    degraded backend) still emits every documented key, as null, instead
    of silently dropping the series."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.iteration import IterationConfig, iterate
    from flink_ml_tpu.models.clustering import kmeans as km

    results["workset_rounds_to_converge"] = None
    results["workset_bsp_rounds"] = None
    results["workset_assign_flops_ratio"] = None
    results["workset_bitexact"] = None
    notes = results["notes"].setdefault("workset", {})
    results["notes"]["workset_metric_version"] = 1

    smoke = _smoke()
    n = 1 << 14 if smoke else 1 << 19
    k, d = (16, 32) if smoke else (64, 64)
    max_iter = 96
    measure = DistanceMeasure.get_instance("euclidean")
    mesh = km.default_mesh()

    # clustered blobs generated ON DEVICE (convergence must actually
    # happen before max_iter — unstructured noise would not converge and
    # the leg would measure nothing)
    @jax.jit
    def gen(key):
        kc, kl, kn = jax.random.split(key, 3)
        centers = jax.random.normal(kc, (k, d), jnp.float32) * 8.0
        lab = jax.random.randint(kl, (n,), 0, k, jnp.int32)
        pts = centers[lab] + jax.random.normal(kn, (n, d), jnp.float32) * 0.4
        return pts

    # shard the batch dim over the mesh's data axis (device->device
    # reshard, nothing crosses the host tunnel) so a multi-device run
    # actually measures the SPMD loop — incl. the mask psum the exit
    # decision rides — and a 1-device host is a no-op placement
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharded = NamedSharding(mesh, P("data"))
    points = jax.device_put(gen(jax.random.PRNGKey(42)), sharded)
    mask = jax.device_put(jnp.ones((n,), jnp.float32), sharded)
    init = km.replicate(points[:k] + 0.0, mesh)
    notes["mesh_devices"] = int(np.prod(list(mesh.shape.values())))

    bsp_body = km.kmeans_epoch_step(measure, k)
    ws_body = km.kmeans_workset_epoch_step(measure, k)
    plan = km._fit_plan(n, d, k, measure, mesh, workset=True)

    def run_bsp():
        return iterate(bsp_body, init, (points, mask), max_epochs=max_iter,
                       config=IterationConfig(mode="fused"))

    def run_ws():
        return iterate(ws_body, init, (points, mask), max_epochs=max_iter,
                       workset=plan.init_workset(mask),
                       config=IterationConfig(mode="fused"))

    from flink_ml_tpu.utils.profiler import fenced_call

    run_bsp(); run_ws()  # compile + warmup
    res_bsp, bsp_wall = fenced_call(run_bsp, probe_of=lambda r: r.state)
    res_ws, ws_wall = fenced_call(run_ws, probe_of=lambda r: r.state)
    c_ws = np.asarray(jax.device_get(res_ws.state))

    c_bsp = np.asarray(jax.device_get(res_bsp.state))
    results["workset_bitexact"] = bool(np.array_equal(c_bsp, c_ws))
    results["workset_rounds_to_converge"] = res_ws.num_epochs
    results["workset_bsp_rounds"] = res_bsp.num_epochs

    frac = np.asarray(
        res_ws.side["epoch_trace"]["active_fraction"], np.float64)
    scored = km.workset_points_scored(frac, n, n)
    unit = 4.0 * k * d            # assign flops per point scored
    bsp_flops = res_bsp.num_epochs * n * unit
    ws_flops = float(scored.sum()) * unit
    results["workset_assign_flops_ratio"] = (
        round(bsp_flops / ws_flops, 2) if ws_flops > 0 else None)
    notes["active_fraction_curve"] = [round(float(f), 4) for f in frac[:32]]
    notes["points_scored_min_frac"] = (
        round(float(scored.min()) / n, 4) if scored.size else None)
    notes["early_exit_flops_ratio"] = round(
        float(res_bsp.num_epochs) / max(res_ws.num_epochs, 1), 2)
    notes["bsp_wall_s"] = round(bsp_wall, 3)
    notes["ws_wall_s"] = round(ws_wall, 3)
    notes["shape"] = f"n={n} k={k} d={d} max_iter={max_iter}"


def _probe_tpu_backend(timeout_s: int = 240) -> bool:
    """Is the axon TPU actually reachable?  During a relay outage the
    first device use blocks ~25 min inside make_c_api_client before
    failing — probing in a SUBPROCESS with a timeout keeps the bench from
    hanging the whole round.  On failure the bench falls back to the CPU
    smoke pass and marks the JSON so the series is not silently
    corrupted."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; import numpy as np; "
             "x = jax.numpy.ones((4,4)) @ jax.numpy.ones((4,4)); "
             "assert float(np.asarray(x)[0,0]) == 4.0; "
             "print(jax.default_backend())"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0 and "tpu" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def bench_widedeep(results: dict) -> None:
    """Wide&Deep two-tower training-step rate (BASELINE.md "configs to
    support", stretch config) at a Criteo-shaped size on one chip:
    13 dense + 26 categorical fields hashed into a 2^20 stacked vocab,
    64-dim embeddings, (1024, 512, 256) MLP — the compute-bound
    counterpart to the memory-bound LR headline (the MLP is MXU matmul
    work, so this leg reports an MFU worth reading).  Times EXACTLY the
    product train step (``build_reference_train_step``: same forward,
    Adam, loss as ``WideDeep.fit``'s epoch body) over a
    ``lax.scan`` of HBM-resident batches — one dispatch per trial,
    device_get fence, min of 3.  FLOP accounting is the analytic MLP +
    wide matmul count (3x forward for fwd+bwd); embedding
    gathers/scatters are excluded, so the reported TFLOP/s is
    conservative."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.recommendation.widedeep import (
        _field_offsets, build_reference_train_step)
    from flink_ml_tpu.ops.emb_grad import emb_grad_route

    smoke = _smoke()
    n_fields, d_dense = 26, 13
    vocab_each = (1 << 20) // n_fields if not smoke else 64
    vocab_sizes = (vocab_each,) * n_fields
    emb_dim = 64 if not smoke else 8
    hidden = (1024, 512, 256) if not smoke else (32, 16)
    batch = (1 << 13) if not smoke else (1 << 8)
    steps = 16 if not smoke else 2

    rng = np.random.default_rng(17)
    offs = _field_offsets(vocab_sizes)
    cat_host = (rng.integers(0, vocab_each,
                             size=(steps, batch, n_fields)).astype(np.int32)
                + offs[None, None, :].astype(np.int32))
    dense = jnp.asarray(
        rng.normal(size=(steps, batch, d_dense)).astype(np.float32))
    cat = jnp.asarray(cat_host)
    y = jnp.asarray(
        rng.integers(0, 2, size=(steps, batch)).astype(np.float32))
    mask = jnp.ones((steps, batch), jnp.float32)
    total_vocab = int(np.sum(vocab_sizes))
    route_g = emb_grad_route(cat_host, total_vocab, placement="gather")
    route_s = emb_grad_route(cat_host, total_vocab, placement="scatter")

    def measure(lazy: bool, route=None) -> float:
        rt = route.stacked_arrays() if route is not None else ()
        train_step, params, opt_state = build_reference_train_step(
            d_dense, vocab_sizes, emb_dim, hidden, lazy_embeddings=lazy,
            route=route)

        @jax.jit
        def run(params, opt_state):
            def step(carry, i):
                p, o = carry
                extra = tuple(a[i] for a in rt)
                p, o, loss = train_step(p, o, dense[i], cat[i], y[i],
                                        mask[i], *extra)
                return (p, o), loss

            (params, opt_state), losses = jax.lax.scan(
                step, (params, opt_state),
                jnp.arange(steps, dtype=jnp.int32))
            return params, opt_state, losses

        from flink_ml_tpu.utils.profiler import fenced_call

        p, o, losses = run(params, opt_state)     # compile + warm
        assert np.all(np.isfinite(np.asarray(losses)))
        trials = []
        for _ in range(3):
            # probe = the loss log: the shared fenced timing idiom
            (p, o, losses), secs = fenced_call(run, p, o,
                                               probe_of=lambda r: r[2])
            trials.append(secs)
        return min(trials) / steps

    step_s = measure(lazy=False, route=route_g)  # product default since
    #   r5: routedEmbeddingGrad 'auto', gather placement (scatter-free)
    scatter_step_s = measure(lazy=False, route=route_s)  # alt placement
    dense_step_s = measure(lazy=False)         # autodiff-scatter baseline
    lazy_step_s = measure(lazy=True)   # opt-in lazyEmbeddingOptimizer

    # analytic matmul FLOPs: wide tower + MLP chain, 3x forward for the
    # backward pass (standard dense-layer accounting)
    dims = [d_dense + n_fields * emb_dim] + list(hidden) + [1]
    mlp_flops = sum(2 * a * b for a, b in zip(dims, dims[1:])) * batch
    fwd = mlp_flops + 2 * d_dense * batch     # + wide dense matvec
    train_flops = 3 * fwd

    # analytic table-traffic bytes/step (VERDICT r4 weak #6: the MLP-only
    # MFU under-reports how memory-bound the step is — this is the
    # denominator the scatter work improves against).  Dense-Adam streams
    # (grad read + m/v/param read+write = 7 passes) over both tables plus
    # the forward gathers; the routed GATHER-placement backward (what
    # step_s times) adds the permute gather, the fold passes, the final
    # row-gather's g_ext read + dense-grad write, and the pos_map read.
    S = batch * n_fields
    tab_bytes = total_vocab * (emb_dim + 1) * 4       # emb + wide, one pass
    adam_streams = 7 * tab_bytes
    fwd_gather = S * (emb_dim + 1) * 4 * 2            # read rows + write out
    routed_extra = ((1 + route_g.fold_passes) * 2 * S * emb_dim * 4
                    + S * emb_dim * 4                 # g_ext read
                    + tab_bytes                       # dense-grad write
                    + total_vocab * 4)                # pos_map read
    hbm_bytes = adam_streams + fwd_gather + routed_extra
    results["widedeep_steps_per_sec"] = round(1.0 / step_s, 1)
    results["notes"]["widedeep"] = {
        "config": (f"{n_fields}x{vocab_each} vocab, emb {emb_dim}, "
                   f"mlp {hidden}, batch {batch}"),
        "step_ms": round(1000 * step_s, 3),
        "rows_per_sec": round(batch / step_s, 1),
        "tflops": round(train_flops / step_s / 1e12, 2),
        "mfu": round(train_flops / step_s / V5E_PEAK_FLOPS, 4),
        "impl": "routed_emb_grad(gather)",
        "scatter_placement_step_ms": round(1000 * scatter_step_s, 3),
        "fold_passes": route_g.fold_passes,
        # achieved HBM rate against the analytic table-traffic floor —
        # v5e HBM is ~819 GB/s, so this column reads as "how close to
        # memory-bound the step runs"
        "hbm_gbps": round(hbm_bytes / step_s / 1e9, 1),
        # autodiff-scatter baseline (the pre-r5 default): same Adam, same
        # loss; difference is the table-gradient scatter implementation
        "dense_step_ms": round(1000 * dense_step_s, 3),
        "dense_rows_per_sec": round(batch / dense_step_s, 1),
        # opt-in lazyEmbeddingOptimizer: Adam state/param updates only at
        # the rows each batch touches (LazyAdam semantics)
        "lazy_step_ms": round(1000 * lazy_step_s, 3),
        "lazy_rows_per_sec": round(batch / lazy_step_s, 1),
    }


def bench_als(results: dict) -> None:
    """ALS chip rate (VERDICT r4 missing #3): epochs/sec of EXACTLY the
    fit-planned epoch body (``als_epoch_step`` — normal-equation
    accumulation scanned in 64k-rating chunks, batched Cholesky solves,
    'highest' matmul precision) on one chip, with a same-math host-numpy
    anchor on a scaled-down replica.  Explicit-feedback ALS-WR config:
    16k users x 4k items, 2M ratings, rank 64."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.recommendation.als import (
        NeqPlan, als_epoch_step)

    smoke = _smoke()
    n_users = (1 << 14) if not smoke else 1 << 8
    n_items = (1 << 12) if not smoke else 1 << 6
    nnz = (1 << 21) if not smoke else 1 << 12
    rank = 64 if not smoke else 8
    epochs = 2
    reg = 0.1

    # host-generated (the sorted plan is a host build); the one-time
    # ~32 MB upload is tolerable even through the tunnel, and every
    # timed trial reuses the resident arrays
    rng = np.random.default_rng(3)
    u_idx = rng.integers(0, n_users, size=nnz).astype(np.int32)
    i_idx = rng.integers(0, n_items, size=nnz).astype(np.int32)
    ratings = rng.normal(size=nnz).astype(np.float32)
    w_host = np.ones(nnz, np.float32)
    f0 = (rng.normal(size=(n_users + n_items, rank)).astype(np.float32)
          / np.sqrt(rank))
    plan_u, plan_v = NeqPlan(u_idx), NeqPlan(i_idx)

    def measure(impl: str) -> float:
        if impl == "sorted":
            plans = (plan_u, plan_v)
            data = tuple(jnp.asarray(a) for a in (
                plan_u.sort_pad(i_idx), plan_u.sort_pad(ratings),
                plan_u.sort_pad(w_host), plan_u.local_rank, plan_u.g_lo,
                plan_v.sort_pad(u_idx), plan_v.sort_pad(ratings),
                plan_v.sort_pad(w_host), plan_v.local_rank, plan_v.g_lo))
            w_slots = (2, 7)        # the two weight arrays in `data`
        else:
            plans = None
            data = (jnp.asarray(u_idx), jnp.asarray(i_idx),
                    jnp.asarray(ratings), jnp.asarray(w_host))
            w_slots = (3,)
        body = als_epoch_step(n_users, n_items, reg, False, 1.0,
                              plans=plans)

        @jax.jit
        def run(U, V, *data):
            def epoch(state, e):
                return body(state, e, data).feedback, None

            (U, V), _ = jax.lax.scan(epoch, (U, V),
                                     jnp.arange(epochs, dtype=jnp.int32))
            return U, V

        from flink_ml_tpu.utils.profiler import fenced_call

        U, V = jnp.asarray(f0[:n_users]), jnp.asarray(f0[n_users:])
        U1, _ = run(U, V, *data)                   # compile + warm
        assert np.all(np.isfinite(np.asarray(U1[:2])))
        trials = []
        for t in range(1, 4):
            # distinct weights per trial (relay-cache defeat)
            dt = list(data)
            for s in w_slots:
                dt[s] = data[s] * (1.0 + t * 1e-6)
            _, secs = fenced_call(run, U, V, *dt,
                                  probe_of=lambda r: r[0][:1])
            trials.append(secs)
        return min(trials) / epochs

    epoch_s = measure("sorted")        # the fit() default since r5
    scatter_epoch_s = measure("scatter")

    # host anchor: the same math (chunked outer-product accumulation +
    # batched solve) on a 1/16-scale replica, rate scaled back — a
    # same-shape full-size host epoch would not fit the bench budget
    sub = 16 if not smoke else 2
    hu, hi, hr = (np.asarray(u_idx[:nnz // sub]) % (n_users // sub),
                  np.asarray(i_idx[:nnz // sub]) % (n_items // sub),
                  np.asarray(ratings[:nnz // sub]))
    hU = np.asarray(f0[:n_users // sub]).copy()
    hV = np.asarray(f0[n_users:n_users + n_items // sub]).copy()

    def host_solve(factors, g_idx, o_idx, r, n_groups):
        A = np.zeros((n_groups, rank, rank), np.float32)
        b = np.zeros((n_groups, rank), np.float32)
        cnt = np.zeros((n_groups,), np.float32)
        for s in range(0, len(g_idx), 1 << 14):
            g, o, rr = g_idx[s:s + (1 << 14)], o_idx[s:s + (1 << 14)], \
                r[s:s + (1 << 14)]
            y = factors[o]
            np.add.at(A, g, y[:, :, None] * y[:, None, :])
            np.add.at(b, g, rr[:, None] * y)
            np.add.at(cnt, g, 1.0)
        A += (reg * np.maximum(cnt, 1.0))[:, None, None] * np.eye(
            rank, dtype=np.float32)[None]
        return np.linalg.solve(A, b[..., None])[..., 0]

    t0 = time.perf_counter()
    hU = host_solve(hV, hu, hi, hr, n_users // sub)
    hV = host_solve(hU, hi, hu, hr, n_items // sub)
    host_epoch_s = (time.perf_counter() - t0) * sub

    results["als_epochs_per_sec"] = round(1.0 / epoch_s, 3)
    results["notes"]["als"] = {
        "config": (f"{n_users}x{n_items}, {nnz} ratings, rank {rank}, "
                   "explicit ALS-WR"),
        "impl": "sorted",
        "epoch_ms": round(1000 * epoch_s, 1),
        "ratings_per_sec": round(2 * nnz / epoch_s, 1),  # both half-epochs
        # the pre-r5 scatter-add normal equations, same solve tail — a
        # chip verdict here confirms (or reverts) the sorted default
        "scatter_epoch_ms": round(1000 * scatter_epoch_s, 1),
        "neq_spans": (plan_u.span, plan_v.span),
        "vs_host_anchor": round(host_epoch_s / epoch_s, 2),
        "host_anchor": (f"same math at 1/{sub} scale x {sub} "
                        f"({host_epoch_s:.2f}s/epoch equivalent)"),
    }


def bench_gbt(results: dict) -> None:
    """GBT chip rate (VERDICT r4 missing #3): trees/sec of EXACTLY the
    fit-planned boosting loop (``train_forest`` — jitted per-level
    histogram/split/route on device, host grad/hess between trees) on a
    512k x 32 binary problem, with a same-algorithm host-numpy
    single-tree anchor."""
    import jax.numpy as jnp

    from flink_ml_tpu.models.common.gbt import GBTConfig, train_forest

    smoke = _smoke()
    n = (1 << 19) if not smoke else 1 << 12
    d = 32 if not smoke else 8
    trees = 8 if not smoke else 2
    depth = 5 if not smoke else 3
    bins = 64

    rng = np.random.default_rng(29)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.3 * rng.normal(size=n) > 0).astype(np.float64)

    def grad_hess(y, pred):
        p = 1.0 / (1.0 + np.exp(-pred))
        return (p - y), np.maximum(p * (1.0 - p), 1e-16)

    from flink_ml_tpu.models.common import gbt as gbt_mod

    cfg = GBTConfig(num_trees=trees, max_depth=depth, max_bins=bins,
                    learning_rate=0.2)

    def timed_forest(hist_impl: str):
        old = gbt_mod.HIST_IMPL
        gbt_mod.HIST_IMPL = hist_impl
        try:
            t0 = time.perf_counter()
            train_forest(X, y, grad_hess, 0.0, cfg)   # compile + warm
            warm = time.perf_counter() - t0
            t0 = time.perf_counter()
            forest = train_forest(X, y, grad_hess, 0.0, cfg)
            return forest, time.perf_counter() - t0, warm
        finally:
            gbt_mod.HIST_IMPL = old

    forest, wall_s, warm_s = timed_forest(gbt_mod.HIST_IMPL)
    # the MXU double-one-hot histogram alternative.  Parity gate on the
    # HISTOGRAMS (allclose — the two impls differ in f32 summation
    # order, so near-tie argmax splits may legitimately pick different
    # features; exact-tree equality would crash the bench on a ULP):
    rng_p = np.random.default_rng(31)
    binned_p = jnp.asarray(rng_p.integers(0, bins, size=(4096, d)),
                           jnp.int32)
    ids_p = jnp.asarray(rng_p.integers(-1, 4, size=4096), jnp.int32)
    gp = jnp.asarray(rng_p.normal(size=4096), jnp.float32)
    hp = jnp.asarray(rng_p.random(4096) + 0.1, jnp.float32)
    gs, hs = gbt_mod._level_histograms_segsum(binned_p, ids_p, gp, hp,
                                              4, d, bins)
    gm, hm = gbt_mod._level_histograms_mxu(binned_p, ids_p, gp, hp,
                                           4, d, bins)
    if not (np.allclose(np.asarray(gs), np.asarray(gm), rtol=1e-4,
                        atol=1e-5)
            and np.allclose(np.asarray(hs), np.asarray(hm), rtol=1e-4,
                            atol=1e-5)):
        raise AssertionError("mxu histograms diverged from segsum")
    forest_mxu, wall_mxu_s, _ = timed_forest("mxu")
    assert np.any(forest.feature[0] >= 0), "GBT bench grew no splits"

    # host anchor: one tree of the same histogram algorithm (quantile
    # bins, (node, feature, bin) G/H sums, best gain split, route) in
    # numpy on the full data
    from flink_ml_tpu.models.common.gbt import bin_features

    binned, _ = bin_features(X, bins)
    g, h = grad_hess(y, np.zeros(n))
    t0 = time.perf_counter()
    node_ids = np.zeros(n, np.int64)
    for level in range(depth):
        n_nodes = 1 << level
        Gh = np.zeros((n_nodes, d, bins), np.float64)
        Hh = np.zeros((n_nodes, d, bins), np.float64)
        rel = node_ids - (n_nodes - 1)
        for f in range(d):
            np.add.at(Gh, (rel, f, binned[:, f]), g)
            np.add.at(Hh, (rel, f, binned[:, f]), h)
        Gc, Hc = Gh.cumsum(2), Hh.cumsum(2)
        Gt, Ht = Gc[:, :, -1:], Hc[:, :, -1:]
        lam = cfg.reg_lambda
        gain = (Gc ** 2 / (Hc + lam) + (Gt - Gc) ** 2 / (Ht - Hc + lam)
                - Gt ** 2 / (Ht + lam))
        best = gain.reshape(n_nodes, -1).argmax(1)
        bf, bb = best // bins, best % bins
        go_left = binned[np.arange(n), bf[rel]] <= bb[rel]
        node_ids = 2 * node_ids + np.where(go_left, 1, 2)
    host_tree_s = time.perf_counter() - t0

    results["gbt_trees_per_sec"] = round(trees / wall_s, 3)
    results["notes"]["gbt"] = {
        "config": f"{n}x{d}, {trees} trees, depth {depth}, {bins} bins",
        "wall_s": round(wall_s, 2),
        "compile_warm_s": round(warm_s, 2),
        "rows_x_trees_per_sec": round(n * trees / wall_s, 1),
        # HIST_IMPL is "auto" since the kernel registry owns the default;
        # report what it resolved to on THIS backend
        "hist_impl": gbt_mod.resolve_hist_impl(),
        # the alternative histogram lowering (double one-hot MXU
        # contraction vs segment_sum scatter-adds); identical trees
        # asserted above — a chip verdict here flips HIST_IMPL
        "mxu_hist_wall_s": round(wall_mxu_s, 2),
        "vs_host_anchor": round((host_tree_s * trees) / wall_s, 2),
        "host_anchor": (f"same histogram algorithm, numpy, "
                        f"{host_tree_s:.2f}s/tree"),
    }


def bench_online_ftrl(results: dict) -> None:
    """OnlineLogisticRegression chip rate (BASELINE.md 'configs to
    support': streaming FTRL): windows/sec of EXACTLY the fit-planned
    sparse FTRL update (``_make_sparse_ftrl_step`` — hashed
    (indices, values) window, one scatter-add gradient, O(d)
    per-coordinate proximal update in HBM) at the Criteo shape, with a
    same-math host-numpy anchor.  Windows stream in fit(); here a
    window stack is HBM-resident and scanned so the dispatch cost
    amortizes — the number is the update-rate ceiling the ingest side
    must feed."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.classification.online_logisticregression \
        import _make_sparse_ftrl_step

    smoke = _smoke()
    window = (1 << 12) if not smoke else 1 << 8
    windows = 16 if not smoke else 2
    d = LR_DIM if not smoke else 1 << 12

    rng = np.random.default_rng(13)
    idx_host = rng.integers(0, d, size=(windows, window, LR_NNZ)
                            ).astype(np.int32)
    vals_host = np.concatenate(
        [rng.normal(size=(windows, window, 13)).astype(np.float32),
         np.ones((windows, window, 26), np.float32)], axis=2)
    y_host = rng.integers(0, 2, size=(windows, window)).astype(np.float32)

    step = _make_sparse_ftrl_step(alpha=0.1, beta=1.0, l1=1e-4, l2=1e-4)
    idx, vals = jnp.asarray(idx_host), jnp.asarray(vals_host)
    y = jnp.asarray(y_host)
    sw = jnp.ones((windows, window), jnp.float32)

    @jax.jit
    def run(state, idx, vals, y, sw):
        def body(state, i):
            state, loss = step(state, idx[i], vals[i], y[i], sw[i])
            return state, loss

        return jax.lax.scan(body, state,
                            jnp.arange(windows, dtype=jnp.int32))

    def fresh():
        return {"w": jnp.zeros((d,), jnp.float32),
                "z": jnp.zeros((d,), jnp.float32),
                "n": jnp.zeros((d,), jnp.float32)}

    from flink_ml_tpu.utils.profiler import fenced_call

    state, losses = run(fresh(), idx, vals, y, sw)
    assert np.all(np.isfinite(np.asarray(losses)))
    trials = []
    for t in range(1, 4):
        swt = sw * (1.0 + t * 1e-6)        # relay-cache defeat
        _, secs = fenced_call(run, fresh(), idx, vals, y, swt,
                              probe_of=lambda r: r[1])
        trials.append(secs)
    win_s = min(trials) / windows

    # host anchor: the same update in numpy on one window, rate scaled
    hw = np.zeros(d, np.float32)
    hz, hn = np.zeros(d, np.float32), np.zeros(d, np.float32)
    t0 = time.perf_counter()
    iw, vw, yw = idx_host[0], vals_host[0], y_host[0]
    margin = np.sum(vw * hw[iw], axis=-1)
    p = 1.0 / (1.0 + np.exp(-margin))
    r = (p - yw) / window
    g = np.zeros(d, np.float32)
    np.add.at(g, iw.reshape(-1), (vw * r[:, None]).reshape(-1))
    sigma = (np.sqrt(hn + g * g) - np.sqrt(hn)) / 0.1
    hz += g - sigma * hw
    hn += g * g
    hw = np.where(np.abs(hz) <= 1e-4, 0.0,
                  -(hz - np.sign(hz) * 1e-4)
                  / ((1.0 + np.sqrt(hn)) / 0.1 + 1e-4)).astype(np.float32)
    host_win_s = time.perf_counter() - t0

    results["ftrl_windows_per_sec"] = round(1.0 / win_s, 1)
    results["notes"]["online_ftrl"] = {
        "config": f"d=2^{int(np.log2(d))}, window {window}, nnz {LR_NNZ}",
        "window_ms": round(1000 * win_s, 2),
        "rows_per_sec": round(window / win_s, 1),
        "vs_host_anchor": round(host_win_s / win_s, 2),
        "host_anchor": f"same update, numpy, {1000 * host_win_s:.1f}ms/window",
    }


def bench_serving(results: dict) -> None:
    """Online serving leg (serving/ subsystem): p50/p99 request latency and
    throughput at 1/8/64 concurrent clients against one warmed LR
    endpoint.  This leg is DESIGNED for the CPU smoke path — what it
    measures is the serving runtime itself (queue + micro-batcher +
    bucketed warm-compiled executors), whose costs are host-side; the
    per-client request stream is single-row/few-row tables, the realistic
    online shape.  Deliberately NOT scaled down off-TPU."""
    from flink_ml_tpu import Table
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)
    from flink_ml_tpu.serving import ModelRegistry, ServingEndpoint

    import threading

    d = 64
    rng = np.random.default_rng(17)
    model = LogisticRegressionModel()
    model.set_model_data(Table({
        "coefficients": rng.normal(size=(1, d)),
        "intercept": np.array([0.1])}))
    feats = Table({"features": rng.normal(size=(1024, d))
                   .astype(np.float32)})

    registry = ModelRegistry()
    warm_t0 = time.perf_counter()
    registry.deploy("lr", model, feats.take(2), max_batch_rows=256)
    warm_s = time.perf_counter() - warm_t0
    endpoint = ServingEndpoint(registry, "lr", max_batch_rows=256,
                               max_wait_ms=1.0,
                               queue_capacity=1 << 14).start()

    serving: dict = {
        "serving_metric_version": 1,
        "config": f"LR dense d={d}, 1-8 row requests, max_batch_rows=256, "
                  "max_wait_ms=1.0",
        "warmup_s": round(warm_s, 3),
    }
    try:
        for clients in (1, 8, 64):
            per_client = 64 if clients < 64 else 16
            latencies: list = []
            lat_lock = threading.Lock()
            errors: list = []

            def client(worker):
                crng = np.random.default_rng(worker)
                mine = []
                try:
                    for _ in range(per_client):
                        start = int(crng.integers(0, 1000))
                        rows = int(crng.integers(1, 9))
                        req = feats.slice(start, start + rows)
                        t0 = time.perf_counter()
                        endpoint.predict(req, timeout=120)
                        mine.append(time.perf_counter() - t0)
                except Exception as exc:  # noqa: BLE001
                    errors.append(repr(exc)[:200])
                with lat_lock:
                    latencies.extend(mine)

            batches_before = endpoint.metrics.batches.value
            wall_t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(w,))
                       for w in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            wall = time.perf_counter() - wall_t0
            n = len(latencies)
            lat = np.asarray(latencies)
            leg = {
                "requests": n,
                "requests_per_sec": round(n / wall, 1),
                "p50_ms": round(1e3 * float(np.quantile(lat, 0.5)), 3)
                if n else None,
                "p99_ms": round(1e3 * float(np.quantile(lat, 0.99)), 3)
                if n else None,
                "batches": endpoint.metrics.batches.value - batches_before,
            }
            if errors:
                leg["errors"] = errors[:3]
            serving[f"clients_{clients}"] = leg
        snap = endpoint.metrics.snapshot()
        serving["shed"] = snap["shed"]
        serving["final_fill_ratio"] = snap["batch_fill_ratio"]
        results["serving_requests_per_sec"] = \
            serving["clients_64"]["requests_per_sec"]
        results["serving_p99_ms"] = serving["clients_64"]["p99_ms"]
    finally:
        endpoint.close()
    results["notes"]["serving"] = serving


def bench_comm(results: dict) -> None:
    """Gradient-reduction comm leg (comm_metric_version 3): per-step
    gradient bytes-on-wire, compression ratio, the exact-vs-topk
    step-time A/B, the **adaptive step-time vs bytes-on-wire Pareto**
    (>= 3 operating points, bytes computed from each run's REALIZED
    per-leaf rungs), the **overlap A/B** — blocking vs one-step-stale
    bucketed reduction at equal density through the SAME
    ``_linear_update_reduced`` scan the trainers run — and (v3) the
    **wire-protocol A/B**: old all-gather vs recursive-halving/doubling
    at densities 0.01/0.05/0.1/0.5, with the analytic per-participant
    byte grid published for 2/4/8 dcn groups, a measured step-time
    Pareto per (density, protocol), and the per-round ``fill_in`` curve
    + dense-switchover rate read back from the rd runs' fill accounting
    state — at the bench LR gradient shape (2^20 f32 weights), through
    the SAME ``parallel/grad_reduce.py`` reducer the trainers adopt.

    On a single-device run there IS no gradient reduction, so every
    measured field is nulled, not faked (the ``gap_closed_fraction``
    convention); the analytic artifacts — payload accounting with the
    hierarchical leg's ICI/DCN fabric split, and the ``bucket_plan``
    (bucket count, bytes per bucket, per-leaf chosen density) — are pure
    shape math and always report, so CPU smoke runs still validate the
    schedule.  Pareto points on single-device runs keep their analytic
    ``bytes_on_wire`` (initial-rung accounting) with ``step_ms`` null.
    Both variants of every A/B are compiled AND warmed before either is
    timed — first-call compile/collective-channel setup used to pollute
    whichever variant ran first."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from flink_ml_tpu.parallel import grad_reduce as GR
    from flink_ml_tpu.parallel.collectives import shard_map_fn
    from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig
    from flink_ml_tpu.parallel.mesh import device_mesh, replicate

    d = 1 << 16 if _smoke() else 1 << 20
    density = 0.1
    buckets = 8
    like = {"w": np.zeros((d,), np.float32)}
    ladder = (0.01, 0.05, density, "exact")
    adaptive_points = {
        # target = tolerated residual/grad norm ratio: thrifty tolerates a
        # hot residual (descends the ladder), faithful pushes toward exact
        "adaptive_thrifty": GradReduceConfig(
            mode="topk", density=density, bucket_count=buckets,
            adaptive=True, adaptive_target=4.0, density_ladder=ladder),
        "adaptive_balanced": GradReduceConfig(
            mode="topk", density=density, bucket_count=buckets,
            adaptive=True, adaptive_target=1.0, density_ladder=ladder),
        "adaptive_faithful": GradReduceConfig(
            mode="topk", density=density, bucket_count=buckets,
            adaptive=True, adaptive_target=0.25, density_ladder=ladder),
    }
    overlap_cfg = GradReduceConfig(mode="topk", density=density,
                                   bucket_count=buckets, overlap=True)
    comm: dict = {
        "comm_metric_version": 3,
        "config": f"dense LR grad d={d}, topk density={density}, "
                  f"int8 block 256, {buckets} buckets, ladder {ladder}",
        "accounting": {
            "topk": GR.payload_bytes(
                like, GradReduceConfig(mode="topk", density=density)),
            "int8": GR.payload_bytes(
                like, GradReduceConfig(mode="int8", block_size=256)),
            # hierarchical: the two fabrics report separately — the
            # compressed DCN hop vs the exact ICI scatter/gather bytes
            "hier_topk": GR.payload_bytes(
                like, GradReduceConfig(mode="topk", density=density,
                                       dcn_axis="dcn"), ici_size=4),
        },
        # the analytic schedule, published even when timing legs skip
        "bucket_plan": GR.bucket_report(like, overlap_cfg),
    }
    n_dev = jax.device_count()
    comm["devices"] = n_dev

    # ---- wire-protocol tier (v3): the analytic old-vs-new byte grid is
    # pure shape math and ALWAYS publishes — per-participant bytes of the
    # all-gather protocol vs the recursive-halving/doubling rounds, per
    # (density, dcn-group-count) cell
    wire_densities = (0.01, 0.05, density, 0.5)
    wire_groups = (2, 4, 8)
    analytic_grid = []
    for dens in wire_densities:
        w_cfg = GradReduceConfig(mode="topk", density=dens)
        for groups in wire_groups:
            w = GR.payload_bytes(like, w_cfg, hop_size=groups)["wire"]
            analytic_grid.append({
                "density": dens, "dcn_groups": groups,
                "rounds": w["rounds"],
                "allgather_bytes": w["allgather_bytes"],
                "rd_bytes_best": w["rd_bytes_best"],
                "rd_bytes_worst": w["rd_bytes_worst"],
                "reduction_vs_allgather_best":
                    w["reduction_vs_allgather_best"],
            })
    comm["wire_protocol"] = {
        "protocol_default": GR.resolved_wire_protocol(
            GradReduceConfig(mode="topk", density=density)),
        "densities": list(wire_densities),
        "dcn_groups": list(wire_groups),
        "analytic": analytic_grid,
    }

    def pareto_point(label, cfg, step_ms, rungs):
        acc = GR.payload_bytes(like, cfg, rungs=rungs)
        point = {"label": label, "step_ms": step_ms,
                 "bytes_on_wire": acc["total_wire_bytes"],
                 "compression_ratio": acc["compression_ratio"]}
        if cfg.adaptive:
            point["per_leaf_density"] = [
                e["density"] for e in
                GR.bucket_report(like, cfg, rungs=rungs)["per_leaf"]]
        return point

    if n_dev < 2:
        # no reduction happens on one device — null, don't fake
        comm["grad_bytes_on_wire_exact"] = None
        comm["grad_bytes_on_wire_topk"] = None
        comm["compression_ratio"] = None
        comm["step_ms_exact"] = None
        comm["step_ms_topk"] = None
        comm["overlap_step_ms_blocking"] = None
        comm["overlap_step_ms_overlapped"] = None
        comm["overlap_speedup"] = None
        # analytic bytes still publish for every point (step_ms null),
        # exact/topk references included so smoke output keeps the
        # baselines the adaptive points compare against
        comm["pareto"] = [
            pareto_point("exact", GradReduceConfig(mode="exact"),
                         None, None),
            pareto_point("topk",
                         GradReduceConfig(mode="topk", density=density),
                         None, None),
        ] + [pareto_point(label, cfg, None, None)
             for label, cfg in adaptive_points.items()]
        # protocol Pareto keeps its analytic bytes (largest-group cell)
        # with step_ms null; the fill curve is a RUN observation — null
        comm["wire_protocol"]["pareto"] = [
            {"density": cell["density"], "protocol": proto,
             "step_ms": None,
             "bytes_on_wire": (cell["rd_bytes_best"] if proto == "rd"
                               else cell["allgather_bytes"])}
            for cell in analytic_grid
            if cell["dcn_groups"] == wire_groups[-1]
            for proto in ("allgather", "rd")]
        comm["wire_protocol"]["fill_in_curve"] = None
        comm["wire_protocol"]["switch_rate"] = None
        comm["wire_protocol"]["rd_bytes_measured"] = None
        results["notes"]["comm"] = comm
        return

    mesh = device_mesh({"data": n_dev})
    dev_spec = P("data")

    def build(cfg):
        def body(g, st):
            red, new_st = GR.reduce_gradients(
                {"w": g[0]}, GR.squeeze_state(st), cfg)
            return red["w"][None], GR.unsqueeze_state(new_st)

        return jax.jit(shard_map_fn(
            body, mesh, in_specs=(P("data", None), dev_spec),
            out_specs=(P("data", None), dev_spec)))

    @jax.jit
    def gen(key):
        return jax.random.normal(key, (n_dev, d), jnp.float32)

    # compile + warm EVERY variant before timing ANY (satellite fix:
    # first-call compile and collective-channel setup polluted whichever
    # variant ran first)
    reduce_cfgs = {"exact": GradReduceConfig(mode="exact"),
                   "topk": GradReduceConfig(mode="topk", density=density),
                   **adaptive_points}
    warmed, states, gens = {}, {}, {}
    for label, cfg in reduce_cfgs.items():
        fn = build(cfg)
        state = GR.init_state(cfg, {"w": jnp.zeros((d,), jnp.float32)},
                              n_dev)
        red, state = fn(gen(jax.random.PRNGKey(0)), state)
        np.asarray(red)  # completion fence
        warmed[label], states[label] = fn, state

    def time_mode(label, trials=8):
        fn, state = warmed[label], states[label]
        gen_fn = gens.get(label, gen)
        t0 = time.perf_counter()
        for i in range(1, trials + 1):
            red, state = fn(gen_fn(jax.random.PRNGKey(i)), state)
        np.asarray(red)
        states[label] = state
        return 1e3 * (time.perf_counter() - t0) / trials

    comm["step_ms_exact"] = round(time_mode("exact"), 3)
    comm["step_ms_topk"] = round(time_mode("topk"), 3)
    acc = comm["accounting"]["topk"]
    comm["grad_bytes_on_wire_exact"] = acc["dense_bytes"]
    comm["grad_bytes_on_wire_topk"] = acc["compressed_bytes"]
    comm["compression_ratio"] = acc["compression_ratio"]

    # ---- adaptive Pareto: measured step time vs analytic bytes at the
    # run's REALIZED rungs (fetched from the evolved reducer state)
    pareto = [pareto_point("exact", reduce_cfgs["exact"],
                           comm["step_ms_exact"], None),
              pareto_point("topk", reduce_cfgs["topk"],
                           comm["step_ms_topk"], None)]
    for label, cfg in adaptive_points.items():
        ms = round(time_mode(label, trials=16), 3)
        rungs = np.asarray(states[label]["rung"])[0]
        pareto.append(pareto_point(label, cfg, ms, rungs))
    comm["pareto"] = pareto

    # ---- wire-protocol A/B (v3): old all-gather vs recursive doubling
    # at each density on the live mesh — measured step time per point,
    # bytes from the rd runs' OWN fill accounting (the allgather side is
    # exact shape math; nothing is faked).  Participant gradients here
    # are CORRELATED — shared signal + per-participant minibatch noise,
    # the data-parallel regime (same weights, different batches) whose
    # top-k support overlap is what the halving/doubling rounds exploit;
    # fully independent supports make the union approach P*k and the
    # doubling broadcast degrade toward allgather parity, which the
    # fill_in curve makes visible rather than hiding.
    @jax.jit
    def gen_corr(key):
        kb, kn = jax.random.split(key)
        base = jax.random.normal(kb, (d,), jnp.float32)
        noise = jax.random.normal(kn, (n_dev, d), jnp.float32)
        return base[None, :] + 0.25 * noise

    wire_pareto = []
    fill_curves = {}
    switch_rates = {}
    for dens in wire_densities:
        for proto in ("allgather", "rd"):
            cfg = GradReduceConfig(mode="topk", density=dens,
                                   wire_protocol=proto)
            label = f"wire_{proto}_{dens}"
            fn = build(cfg)
            st = GR.init_state(cfg, {"w": jnp.zeros((d,), jnp.float32)},
                               n_dev)
            red, st = fn(gen_corr(jax.random.PRNGKey(0)), st)
            np.asarray(red)              # compile + warm before timing
            warmed[label], states[label] = fn, st
            gens[label] = gen_corr
            ms = round(time_mode(label), 3)
            acc = GR.payload_bytes(
                like, cfg, hop_size=n_dev,
                fill=states[label].get("fill"))
            w = acc["wire"]
            wire_pareto.append({
                "density": dens, "protocol": proto, "step_ms": ms,
                "bytes_on_wire": (w["rd_bytes_measured"]
                                  if proto == "rd"
                                  else w["allgather_bytes"])})
            if proto == "rd":
                fill_curves[str(dens)] = w["fill_rounds_measured"]
                switch_rates[str(dens)] = w["switch_rate_measured"]
    comm["wire_protocol"]["pareto"] = wire_pareto
    comm["wire_protocol"]["fill_in_curve"] = fill_curves
    comm["wire_protocol"]["switch_rate"] = switch_rates
    comm["wire_protocol"]["rd_bytes_measured"] = {
        p["density"]: p["bytes_on_wire"] for p in wire_pareto
        if p["protocol"] == "rd"}

    # ---- overlap A/B: blocking vs one-step-stale bucketed reduction at
    # EQUAL density, through the real _linear_update_reduced scan (the
    # program every dense data-parallel fit runs)
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import (
        GR_STATE_KEY,
        SGDConfig,
        _linear_update_reduced,
    )

    steps = 8
    batch = n_dev * (64 if _smoke() else 256)
    d_ov = 1 << 12 if _smoke() else 1 << 14
    rng = np.random.default_rng(11)
    Xw = jax.device_put(
        rng.normal(size=(steps, batch, d_ov)).astype(np.float32) / 16.0,
        NamedSharding(mesh, P(None, "data", None)))
    yv = jax.device_put(
        (rng.random(size=(steps, batch)) > 0.5).astype(np.float32),
        NamedSharding(mesh, P(None, "data")))
    wv = jax.device_put(np.ones((steps, batch), np.float32),
                        NamedSharding(mesh, P(None, "data")))

    def build_loop(gr_cfg):
        scfg = SGDConfig(learning_rate=0.1, grad_reduce=gr_cfg)
        update = _linear_update_reduced(LOSSES["logistic"], scfg, mesh)

        def run(params):
            def step(p, i):
                return update(p, Xw[i], yv[i], wv[i])

            return lax.scan(step, params,
                            jnp.arange(steps, dtype=jnp.int32))

        init = replicate({
            "w": jnp.zeros((d_ov,), jnp.float32),
            "b": jnp.zeros((), jnp.float32),
            GR_STATE_KEY: GR.init_state(
                gr_cfg, {"w": jnp.zeros((d_ov,), jnp.float32),
                         "b": jnp.zeros((), jnp.float32)}, n_dev),
        }, mesh)
        return jax.jit(run), init

    blocking_cfg = GradReduceConfig(mode="topk", density=density,
                                    bucket_count=buckets)
    loops = {}
    for label, cfg in (("blocking", blocking_cfg),
                       ("overlapped", overlap_cfg)):
        run, init = build_loop(cfg)
        params, losses = run(init)       # compile + warm both first
        np.asarray(losses)
        loops[label] = (run, init)

    def time_loop(label, trials=4):
        run, init = loops[label]
        t0 = time.perf_counter()
        for _ in range(trials):
            params, losses = run(init)
        np.asarray(losses)
        return 1e3 * (time.perf_counter() - t0) / (trials * steps)

    comm["overlap_step_ms_blocking"] = round(time_loop("blocking"), 3)
    comm["overlap_step_ms_overlapped"] = round(time_loop("overlapped"), 3)
    comm["overlap_speedup"] = (
        round(comm["overlap_step_ms_blocking"]
              / comm["overlap_step_ms_overlapped"], 3)
        if comm["overlap_step_ms_overlapped"] else None)
    results["notes"]["comm"] = comm


def bench_pipeline(results: dict) -> None:
    """Operator-chaining leg (pipeline_metric_version 1): stagewise vs
    fused A/B for a 5-stage preprocess+score pipeline (standard -> minmax
    -> maxabs -> PCA -> LR) through ``api/chain.py``.

    Reported per transform call: the jitted-dispatch count (stagewise =
    one per chainable stage, analytic; fused = measured segment runs via
    ``chain.dispatch_count``), the exact host<->device byte accounting
    (stagewise moves every stage's consumed+produced columns; fused moves
    segment entry + fetched columns once), and the measured wall-time
    A/B.  The serving sub-leg runs the PR 2 client-sweep shape (64
    clients, 1-8 row requests) against ONE endpoint serving the whole
    fused pipeline and records p50/p99.  Fields are nulled (never faked)
    when the fused plan cannot build."""
    import threading

    from flink_ml_tpu import PipelineModel, Table
    from flink_ml_tpu.api import chain
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)
    from flink_ml_tpu.models.feature.pca import PCA
    from flink_ml_tpu.models.feature.scalers import (
        MaxAbsScaler,
        MinMaxScaler,
        StandardScaler,
    )
    from flink_ml_tpu.serving import ModelRegistry, ServingEndpoint

    rows = (1 << 17) if not _smoke() else 1 << 12
    d = 64
    rng = np.random.default_rng(23)
    X = rng.normal(size=(rows, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    table = Table({"features": X, "label": y})

    s1 = StandardScaler().set_output_col("std").fit(table)
    t1 = s1.transform(table)[0]
    s2 = (MinMaxScaler().set_features_col("std").set_output_col("mm")
          .fit(t1))
    t2 = s2.transform(t1)[0]
    s3 = (MaxAbsScaler().set_features_col("mm").set_output_col("ma")
          .fit(t2))
    t3 = s3.transform(t2)[0]
    s4 = PCA().set_k(16).set_features_col("ma").set_output_col("pc").fit(t3)
    t4 = s4.transform(t3)[0]
    lr = (LogisticRegression().set_features_col("pc").set_max_iter(3)
          .fit(t4))
    pm = PipelineModel([s1, s2, s3, s4, lr])
    feats = table.drop("label")

    pipe: dict = {
        "pipeline_metric_version": 1,
        "config": f"std->minmax->maxabs->pca16->LR, {rows}x{d} f32, "
                  "5 stages",
        "stages": 5,
    }
    plan = pm._chain_plan([feats])
    if plan is None:
        pipe.update({k: None for k in (
            "segments", "dispatches_stagewise", "dispatches_fused",
            "bytes_stagewise", "bytes_fused", "transfer_reduction",
            "transform_ms_stagewise", "transform_ms_fused",
            "fused_speedup", "serving_p50_ms", "serving_p99_ms",
            "serving_requests_per_sec")})
        pipe["plan_error"] = "fused plan did not build"
        results["notes"]["pipeline"] = pipe
        return

    segments = plan.segments
    pipe["segments"] = len(segments)
    pipe["chainable_stages"] = plan.num_fused_stages

    # exact byte accounting at the bench row count (f32 after the chain's
    # dtype normalization): stagewise = per stage consumed+produced,
    # fused = segment entry + fetch, once
    # widths depend only on trailing shapes, so probe the output schema
    # on a tiny slice instead of transforming the full bench table
    widths = {}
    for t in (feats, t1, t2, t3, t4, pm.transform(feats.take(8))[0]):
        for name, (shape, _) in t.schema().items():
            widths.setdefault(name, int(np.prod(shape)) if shape else 1)
    stagewise_bytes = 0
    fused_bytes = 0
    for seg in segments:
        for kernel in seg.kernels:
            for name in kernel.consumes:
                stagewise_bytes += 4 * rows * widths[name]
            for name in kernel.produces:
                # a terminal's staging column (margins/assignments) never
                # appears in any Table schema; it is a width-1 row vector
                stagewise_bytes += 4 * rows * widths.get(name, 1)
        h2d, d2h = seg.transfer_bytes(rows)
        fused_bytes += h2d + d2h
    pipe["bytes_stagewise"] = stagewise_bytes
    pipe["bytes_fused"] = fused_bytes
    pipe["transfer_reduction"] = round(
        stagewise_bytes / max(fused_bytes, 1), 2)
    pipe["dispatches_stagewise"] = plan.num_fused_stages
    d0 = chain.dispatch_count()
    pm.transform(feats)
    pipe["dispatches_fused"] = chain.dispatch_count() - d0

    # publish NOW with the un-measured legs nulled: an exception in the
    # timing/serving sub-legs below (main() records it as a note) must
    # not discard the dispatch/byte A/B already measured — fields stay
    # nulled, never faked, and the dict updates in place on success
    for key in ("transform_ms_stagewise", "transform_ms_fused",
                "fused_speedup", "serving_p50_ms", "serving_p99_ms",
                "serving_requests_per_sec"):
        pipe[key] = None
    results["notes"]["pipeline"] = pipe

    def _time(fn, reps=5):
        fn()                                   # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return 1e3 * (time.perf_counter() - t0) / reps

    with chain.chain_disabled():
        pipe["transform_ms_stagewise"] = round(
            _time(lambda: pm.transform(feats)), 2)
    pipe["transform_ms_fused"] = round(_time(lambda: pm.transform(feats)), 2)
    pipe["fused_speedup"] = round(
        pipe["transform_ms_stagewise"] / max(pipe["transform_ms_fused"],
                                             1e-9), 2)

    # fused serving: ONE endpoint runs preprocess+score per micro-batch
    # (the PR 2 sweep shape: 64 clients, 1-8 row requests)
    registry = ModelRegistry()
    registry.deploy("pipeline", pm, feats.take(2), max_batch_rows=256)
    endpoint = ServingEndpoint(registry, "pipeline", max_batch_rows=256,
                               max_wait_ms=1.0,
                               queue_capacity=1 << 14).start()
    try:
        clients, per_client = 64, 16
        latencies: list = []
        lat_lock = threading.Lock()

        def client(worker):
            crng = np.random.default_rng(worker)
            mine = []
            for _ in range(per_client):
                start = int(crng.integers(0, min(rows - 8, 1000)))
                req = feats.slice(start, start + int(crng.integers(1, 9)))
                t0 = time.perf_counter()
                endpoint.predict(req, timeout=120)
                mine.append(time.perf_counter() - t0)
            with lat_lock:
                latencies.extend(mine)

        wall_t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        wall = time.perf_counter() - wall_t0
        lat = np.asarray(latencies)
        pipe["serving_p50_ms"] = (round(1e3 * float(np.quantile(lat, 0.5)),
                                        3) if len(lat) else None)
        pipe["serving_p99_ms"] = (round(1e3 * float(np.quantile(lat, 0.99)),
                                        3) if len(lat) else None)
        pipe["serving_requests_per_sec"] = round(len(lat) / wall, 1)
    finally:
        endpoint.close()
    results["pipeline_fused_speedup"] = pipe["fused_speedup"]
    results["notes"]["pipeline"] = pipe


def bench_recovery(results: dict) -> None:
    """Self-healing leg (recovery_metric_version 1): a resilient_fit run
    with an injected mid-epoch crash PLUS a torn newest checkpoint at a
    fixed chunk boundary.  Reports MTTR (detect -> restore complete,
    where training resumes) and steps-replayed (crash step minus the
    restored cut's step — the work the fallback to the previous valid
    cut re-paid), plus a bit-exactness verdict vs the same-run
    uninterrupted oracle.  Measured fields start null and stay null
    (never faked) if the chaos run cannot complete."""
    import tempfile

    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
    from flink_ml_tpu.robustness import (FaultPlan, RecoveryReport,
                                         RetryPolicy, resilient_fit)

    recovery: dict = {
        "recovery_metric_version": 1,
        "config": "LR dense 4096x32, 16 batches/epoch, 3 epochs, W=4, "
                  "cut every 4 steps; torn cut + crash in epoch 1",
        "mttr_s": None,
        "steps_replayed": None,
        "restarts": None,
        "crash_step": None,
        "restored_step": None,
        "recovered_bitexact": None,
        "chaos_wall_s": None,
    }
    results["notes"]["recovery"] = recovery

    n, d, batch = 4096, 32, 256      # 16 batches/epoch
    rng = np.random.default_rng(23)
    true_w = rng.normal(size=(d,))
    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "cache")
        writer = DataCacheWriter(cache, segment_rows=1024)
        for _ in range(n // 1024):
            X = rng.normal(size=(1024, d)).astype(np.float32)
            writer.append({"features": X,
                           "label": (X @ true_w > 0).astype(np.float32)})
        writer.finish()
        cfg = SGDConfig(learning_rate=0.3, max_epochs=3, tol=0.0)
        kw = dict(num_features=d, config=cfg, cache_decoded=False,
                  steps_per_dispatch=4)

        def reader():
            return DataCacheReader(cache, batch_rows=batch)

        oracle, _ = sgd_fit_outofcore(logistic_loss, reader, **kw)

        # 17 pulls/epoch (16 batches + end-of-stream probe).  Cuts every
        # 4 steps at W=4 chunk boundaries: 4 mid-epoch + 1 boundary
        # write per epoch.  Epoch-1 write 7 (its 3rd mid cut, step 12)
        # commits torn; the crash fires at pull 31 (epoch 1, batch 14),
        # so recovery must skip the torn step-28 cut and replay from the
        # step-24 one.
        plan = (FaultPlan(seed=1)
                .inject("checkpoint.write", at=7, kind="torn")
                .inject("source.pull", at=31, kind="crash"))
        from flink_ml_tpu.iteration.checkpoint import CheckpointManager

        report = RecoveryReport()
        manager = CheckpointManager(CheckpointConfig(
            os.path.join(td, "ck"), max_to_keep=8))
        t0 = time.perf_counter()
        with plan:
            state, _ = resilient_fit(
                sgd_fit_outofcore, logistic_loss,
                lambda: plan.wrap_source(reader()),
                checkpoint=manager, checkpoint_every_steps=4,
                max_restarts=2,
                backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
                report=report, **kw)
        chaos_wall = time.perf_counter() - t0

        crash = next((f for f in plan.fires if f[0] == "source.pull"),
                     None)
        recovery["restarts"] = report.restarts
        recovery["chaos_wall_s"] = round(chaos_wall, 3)
        if crash is not None:
            # pull index -> global batch index: 17 pulls/epoch, 16 real
            epoch_of = crash[1] // 17
            recovery["crash_step"] = crash[1] - epoch_of
        recovery["restored_step"] = manager.last_restored_step
        if report.events and report.events[0].mttr_s is not None:
            recovery["mttr_s"] = round(report.events[0].mttr_s, 4)
        if (recovery["crash_step"] is not None
                and manager.last_restored_step is not None):
            recovery["steps_replayed"] = (recovery["crash_step"]
                                          - manager.last_restored_step)
        recovery["recovered_bitexact"] = bool(
            np.array_equal(state.coefficients, oracle.coefficients)
            and state.intercept == oracle.intercept)


def bench_online(results: dict) -> None:
    """Continuous-learning leg (online_metric_version 1, ISSUE 7):

    - ``publish_delta_ms`` vs ``publish_full_swap_ms``: the device-
      resident buffer swap (rebind into already-compiled executors)
      against the full adapt->warm->swap deploy of the same model —
      the publish-latency headline.
    - ``freshness_lag_ms``: event -> served, measured through the real
      driver loop (WAL ingest stamp of a cut's last window to the
      moment its generation is live).
    - ``held_requests_per_sec`` / ``held_p99_ms``: throughput a
      4-client barrage sustains WHILE publishes land continuously,
      with ``dropped_requests`` counted (must be 0).

    Measured fields are published pre-nulled and filled as each
    sub-leg lands, so a mid-leg failure reports honest nulls, never
    fakes."""
    import tempfile
    import threading
    import time as _time

    from flink_ml_tpu import Table
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.online import (ContinuousLearner, DeltaEncoder,
                                     DeltaPublisher, params_of_model)
    from flink_ml_tpu.serving import serve_model

    online: dict = {
        "online_metric_version": 1,
        "publish_delta_ms": None,
        "publish_full_swap_ms": None,
        "publish_speedup": None,
        "freshness_lag_ms": None,
        "publishes_observed": None,
        "held_requests_per_sec": None,
        "held_p99_ms": None,
        "publishes_during_hold": None,
        "dropped_requests": None,
    }
    results["notes"]["online"] = online

    D, B, NWIN = 16, 64, 24
    rng = np.random.default_rng(17)

    def window(i):
        r = np.random.default_rng(4000 + i)
        X = r.normal(size=(B, D)).astype(np.float32)
        return Table({"features": X,
                      "label": (X[:, 0] > 0).astype(np.float32)})

    boot_t = window(0)
    boot = LogisticRegression().set_max_iter(2).fit(boot_t)
    feats = boot_t.drop("label")
    endpoint = serve_model(boot, feats.take(2), max_batch_rows=64,
                           max_wait_ms=0.5)
    try:
        # -- publish latency: delta buffer swap vs full deploy ----------
        pub = DeltaPublisher(endpoint.registry, "default",
                             metrics=endpoint.metrics)
        enc = DeltaEncoder()
        p = params_of_model(boot)
        pub.apply(enc.encode(1, p, pub.stats))
        enc.ack()
        delta_ts = []
        for step in range(2, 22):
            p = {"w": p["w"] + np.float32(0.01), "b": p["b"]}
            r = pub.apply(enc.encode(step, p, pub.stats))
            enc.ack()
            delta_ts.append(r.publish_s)
        online["publish_delta_ms"] = round(
            1e3 * float(np.median(delta_ts)), 4)
        full_ts = []
        for i in range(5):
            other = LogisticRegression().set_max_iter(2).fit(window(i))
            t0 = _time.perf_counter()
            endpoint.hot_swap(other)     # full path: adapt + warm + swap
            full_ts.append(_time.perf_counter() - t0)
        online["publish_full_swap_ms"] = round(
            1e3 * float(np.median(full_ts)), 4)
        online["publish_speedup"] = round(
            float(np.median(full_ts) / max(np.median(delta_ts), 1e-9)), 2)

        # -- freshness lag through the real driver loop -----------------
        event_at: dict = {}

        def stamped(n):
            for i in range(n):
                event_at[i] = _time.perf_counter()
                yield window(i)

        lags = []

        class _Spy(DeltaPublisher):
            def apply(self, update):
                res = super().apply(update)
                if res.mode != "noop":
                    # the cut at step s trained windows [0, s): lag is
                    # measured from the NEWEST window in the cut
                    lags.append(_time.perf_counter()
                                - event_at[int(res.step) - 1])
                return res

        with tempfile.TemporaryDirectory() as td:
            learner = ContinuousLearner(
                loss_fn=logistic_loss, num_features=D,
                source=stamped(NWIN), wal_dir=os.path.join(td, "wal"),
                endpoint=endpoint, batch_rows=B,
                checkpoint=CheckpointConfig(os.path.join(td, "ck")),
                publish_every_steps=4)
            learner.publisher = _Spy(endpoint.registry, "default",
                                     metrics=endpoint.metrics)
            learner.run(max_windows=NWIN)
        if lags:
            online["freshness_lag_ms"] = round(
                1e3 * float(np.median(lags)), 3)
            online["publishes_observed"] = len(lags)

        # -- req/s held during continuous publishes ---------------------
        stop = _time.perf_counter() + 1.5
        served = [0, 0, 0, 0]           # one slot per client: += on a
        errors: list = []               # shared slot races under the GIL

        def client(k):
            r = np.random.default_rng(k)
            while _time.perf_counter() < stop:
                try:
                    endpoint.predict(feats.take(1 + int(r.integers(32))),
                                     timeout=10.0)
                    served[k] += 1
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(4)]
        t0 = _time.perf_counter()
        for t in threads:
            t.start()
        pubs = 0
        p = params_of_model(
            endpoint.registry.current("default").servable.model)
        enc2 = DeltaEncoder()
        pub2 = DeltaPublisher(endpoint.registry, "default",
                              metrics=endpoint.metrics)
        step = 1000
        while _time.perf_counter() < stop:
            p = {"w": p["w"] + np.float32(0.001), "b": p["b"]}
            pub2.apply(enc2.encode(step, p, pub2.stats))
            enc2.ack()
            pubs += 1
            step += 1
            _time.sleep(0.02)
        for t in threads:
            t.join(15.0)
        wall = _time.perf_counter() - t0
        online["held_requests_per_sec"] = round(sum(served) / wall, 1)
        online["held_p99_ms"] = endpoint.metrics.snapshot().get(
            "latency_p99_ms")
        online["publishes_during_hold"] = pubs
        online["dropped_requests"] = len(errors)
    finally:
        endpoint.close()


def _elastic_child() -> None:
    """Child process for :func:`bench_elastic` — runs on a fresh virtual
    8-device CPU fleet (the parent sets XLA_FLAGS/JAX_PLATFORMS) so the
    leg never has to repartition the parent's backend mid-bench.  Prints
    ONE JSON line with the measured fields."""
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as _np

    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.iteration.checkpoint import (
        CheckpointConfig,
        CheckpointManager,
    )
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
    from flink_ml_tpu.parallel.elastic import ElasticCoordinator
    from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig
    from flink_ml_tpu.robustness import (
        FaultPlan,
        RecoveryReport,
        RetryPolicy,
        resilient_fit,
    )

    out: dict = {"devices": jax.device_count()}
    n, d, batch, chips = 1920, 16, 240, 2
    rng = _np.random.default_rng(29)
    true_w = rng.normal(size=(d,))
    gr = GradReduceConfig(mode="topk", density=0.25, bucket_count=2,
                          overlap=True, axis="data", dcn_axis="dcn")

    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "cache")
        writer = DataCacheWriter(cache, segment_rows=480)
        for _ in range(n // 480):
            X = rng.normal(size=(480, d)).astype(_np.float32)
            writer.append({"features": X,
                           "label": (X @ true_w > 0).astype(_np.float32)})
        writer.finish()

        def reader():
            return DataCacheReader(cache, batch_rows=batch)

        def fit(coord, ck, **kw):
            cfg = SGDConfig(learning_rate=0.3, max_epochs=4, tol=0.0,
                            grad_reduce=gr)
            info: dict = {}
            state, log = sgd_fit_outofcore(
                logistic_loss, reader, num_features=d, config=cfg,
                mesh=coord.mesh(), membership=coord,
                cache_decoded=False, steps_per_dispatch=2,
                checkpoint=ck, checkpoint_every_steps=2,
                stream_info=info, **kw)
            return state, log, info

        # -- step-time vs fleet size (warm epochs only: epoch 0 pays
        # the compile; per-step wall over the 8-batch epochs after it)
        steps = n // batch
        by_fleet = {}
        for workers in (1, 2, 4):
            coord = ElasticCoordinator(chips_per_worker=chips,
                                       initial_workers=workers)
            _, _, info = fit(coord, CheckpointConfig(
                os.path.join(td, f"ck_f{workers}"), max_to_keep=99))
            warm = info["epoch_seconds"][1:]
            by_fleet[str(workers)] = round(
                1000.0 * float(_np.mean(warm)) / steps, 3)
        out["step_ms_by_fleet"] = by_fleet

        # -- resize-pause + exactness: a join at chunk boundary 2 vs a
        # fixed fleet of the new size restoring the same cut
        coord = ElasticCoordinator(chips_per_worker=chips,
                                   initial_workers=2)
        plan = FaultPlan().inject(coord.SCOPE, at=2, kind="join")
        report = RecoveryReport()
        cfgE = SGDConfig(learning_rate=0.3, max_epochs=4, tol=0.0,
                         grad_reduce=gr)
        t0 = time.perf_counter()
        with plan:
            state_e, log_e = resilient_fit(
                sgd_fit_outofcore, logistic_loss,
                lambda: plan.wrap_source(reader()),
                num_features=d, config=cfgE, cache_decoded=False,
                steps_per_dispatch=2, checkpoint_every_steps=2,
                checkpoint=CheckpointConfig(os.path.join(td, "ck_e"),
                                            max_to_keep=99),
                elastic=coord,
                backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
                report=report)
        out["elastic_wall_s"] = round(time.perf_counter() - t0, 3)
        ev = next((e for e in report.events if e.kind == "resize"), None)
        out["resizes"] = report.resizes
        out["resize_pause_s"] = (round(ev.mttr_s, 4)
                                 if ev and ev.mttr_s is not None else None)
        # replay = steps between the restored cut and the boundary that
        # requested the resize — 0 when the boundary cut landed intact
        out["resize_steps_replayed"] = (
            None if ev is None or ev.restored_step is None
            else 6 - int(ev.restored_step))

        # fixed fleet of the new size from the same cut
        ck_fix = os.path.join(td, "ck_fix")
        os.makedirs(ck_fix)
        shutil.copytree(os.path.join(td, "ck_f2", "ckpt-00000006"),
                        os.path.join(ck_fix, "ckpt-00000006"))
        coord3 = ElasticCoordinator(chips_per_worker=chips,
                                    initial_workers=3)
        state_b, log_b, _ = fit(
            coord3, CheckpointManager(CheckpointConfig(ck_fix,
                                                       max_to_keep=99)),
            resume=True)
        out["elastic_bitexact"] = bool(
            _np.array_equal(state_e.coefficients, state_b.coefficients)
            and state_e.intercept == state_b.intercept
            and list(log_e) == list(log_b))
    print(json.dumps(out))


def bench_elastic(results: dict) -> None:
    """Elastic-training leg (elastic_metric_version 1, ISSUE 15):
    step-time vs fleet size and the resize-pause wall.

    Membership elasticity is a host/collective-layout story, not a
    kernel story, so the leg measures on a virtual 8-device CPU fleet
    in a SUBPROCESS — the parent's backend (TPU or single-device CPU)
    is never repartitioned mid-bench, and the leg produces real numbers
    on every host.  Reported: per-step wall at fleet sizes 1/2/4 (x2
    chips, topk+overlap hierarchical grad_reduce — the elastic
    posture), the resize pause (detect -> restore complete, the
    supervisor's ``kind="resize"`` event MTTR), steps replayed by the
    resize (0 at a boundary cut by construction), and the bit-exactness
    verdict of the resized run vs a fixed fleet of the new size
    restoring the same cut.  Measured fields start null and stay null
    (never faked) if the child fails."""
    import subprocess
    import sys

    elastic: dict = {
        "elastic_metric_version": 1,
        "config": "LR dense 1920x16, 8 batches/epoch, W=2, cut every 2 "
                  "steps; topk0.25+overlap hier (dcn x data), 2 chips/"
                  "worker; fleet sweep 1/2/4 workers; join at boundary 2",
        "backend": "virtual-cpu-8",
        "devices": None,
        "step_ms_by_fleet": None,
        "resize_pause_s": None,
        "resize_steps_replayed": None,
        "resizes": None,
        "elastic_wall_s": None,
        "elastic_bitexact": None,
    }
    results["notes"]["elastic"] = elastic

    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import bench; bench._elastic_child()"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env, capture_output=True, text=True, timeout=600)
        if r.returncode != 0:
            raise RuntimeError(
                f"elastic child rc={r.returncode}: {r.stderr[-300:]}")
        elastic.update(json.loads(r.stdout.strip().splitlines()[-1]))
    except Exception as exc:   # noqa: BLE001 — nulls stay null
        elastic["elastic_error"] = repr(exc)[:300]


def bench_autoscale(results: dict) -> None:
    """Autoscaling control-plane leg (autoscale_metric_version 1,
    ISSUE 17): the unified controller vs a static 50/50 train/serve
    split over the SAME compressed 24h diurnal replay — the two axes
    the acceptance names, both measured, never faked:

    - **SLO-violation minutes**: compressed minutes in which the
      interactive class either shed or finished a tick with backlog
      (work waited longer than one 15-min tick — an SLO miss by
      construction).
    - **Chip-idle fraction**: fleet-level idle, mean over the day —
      serving chips idle for the windowed complement of their busy
      time, learner chips always productive.  The static split's cost
      is 4 serving chips parked all night; the controller's cost is
      extra serving chips held at partial utilisation during the peak
      to hold the SLO.  Both costs land in this one number.

    The replay is deterministic on ONE fake clock (the injectable-clock
    satellite): a queue-mechanics stub whose service time is
    ``chip_s_per_row * rows / serving_chips`` — capacity follows the
    placement, which is the whole point of moving chips — driven
    through the REAL SharedScheduler (WFQ, class sheds, idle window),
    PlacementStore, AutoscalePolicy, and ElasticCoordinator boundary
    seam.  No wall time is measured anywhere in the leg, so the
    numbers are load-model outputs: exact, reproducible, and honest
    about being a model (``config`` says so).

    ``controller_dominates`` is computed from the two axes (strictly
    better on >= 1, worse on neither), never asserted into truth."""
    from flink_ml_tpu import Table
    from flink_ml_tpu.autoscale import (AutoscaleController,
                                        PlacementStore, PolicyConfig)
    from flink_ml_tpu.obs.tree import default_tree
    from flink_ml_tpu.parallel.elastic import ElasticCoordinator
    from flink_ml_tpu.serving import (ModelRegistry, ServingOverloadedError,
                                      SharedScheduler)

    a: dict = {
        "autoscale_metric_version": 1,
        "config": "8-chip fleet, 96 ticks x 900s (24h compressed), fake "
                  "clock load model; peak 9h-21h: 28x16-row interactive "
                  "req/tick, night: 1 inter + 1 bulk; 9 chip-s/row; "
                  "static 4/4 vs controller (min_serving 2, dwell 1800s, "
                  "queue_high 48, idle_high 0.35)",
        "slo_violation_minutes": {"controller": None, "static": None},
        "chip_idle_fraction": {"controller": None, "static": None},
        "interactive_sheds": {"controller": None, "static": None},
        "max_learner_staleness_s": {"controller": None, "static": None},
        "serving_chips_range": {"controller": None, "static": None},
        "controller_decisions": None,
        "controller_actuations": None,
        "placement_generations": None,
        "controller_dominates": None,
    }
    results["notes"]["autoscale"] = a
    # headline fields: pre-nulled at leg entry, never faked
    results.setdefault("autoscale_slo_violation_minutes", None)
    results.setdefault("autoscale_idle_fraction", None)
    results.setdefault("autoscale_controller_dominates", None)

    class _Clock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

        def advance(self, dt):
            self.t += dt

    total_chips, dt, ticks = 8, 900.0, 96
    chip_s_per_row = 9.0

    def replay(controlled: bool) -> dict:
        clock = _Clock()
        state = {"chips": 4}      # serving chips the stub divides across

        class _Stub:
            """Queue-mechanics stub: service time scales inversely with
            the placed serving chips — capacity follows placement."""

            ready = True
            warmup_report = None

            def __init__(self, model, example, **kwargs):
                self.max_batch_rows = kwargs.get("max_batch_rows", 256)
                self.output_cols = None

            def warm_up(self):
                return self

            def check_schema(self, table):
                pass

            def bucket_for(self, rows):
                return max(8, rows)

            def predict(self, table):
                clock.advance(chip_s_per_row * table.num_rows
                              / state["chips"])
                return table

        rng = np.random.default_rng(17)
        feats = Table({"features": rng.normal(size=(64, 4))})
        scheduler = SharedScheduler(
            ModelRegistry(servable_factory=_Stub), max_batch_rows=64,
            max_wait_ms=0.0, queue_capacity=128, busy_clock=clock)
        inter = scheduler.add_tenant("inter", object(), feats.take(2),
                                     slo="interactive")
        scheduler.add_tenant("bulk", object(), feats.take(2), slo="bulk")
        # placeholder device pool: the replay exercises the coordinator's
        # membership/boundary seam, never mesh() — independent of how
        # many real devices this bench process sees
        coord = ElasticCoordinator(chips_per_worker=1, initial_workers=4,
                                   min_workers=1, clock=clock,
                                   devices=list(range(total_chips)))
        store = PlacementStore(total_chips, chips_per_worker=1,
                               clock=clock)
        store.publish({"inter": [0, 1, 2, 3], "bulk": [0, 1, 2, 3]}, 4)
        controller = None
        if controlled:
            controller = AutoscaleController.build(
                default_tree(scheduler=scheduler, elastic=coord),
                store=store, scheduler=scheduler, elastic=coord,
                clock=clock,
                policy_config=PolicyConfig(
                    p99_target_ms=250.0, total_chips=total_chips,
                    chips_per_worker=1, queue_high=48, idle_high=0.35,
                    min_dwell_s=1800.0, min_serving_chips=2,
                    min_learner_workers=1))

        violation_min = 0.0
        idle_sum = 0.0
        sheds = 0
        chips_seen = set()
        learner_last = 0.0
        max_stale = 0.0
        for tick in range(ticks):
            # absolute tick grid: an in-flight batch completing past the
            # boundary eats the NEXT tick's budget — overload accumulates
            # as backlog instead of silently stretching the day
            t0 = clock.t
            t_end = (tick + 1) * dt
            hour = (tick * dt / 3600.0) % 24.0
            peak = 9.0 <= hour < 21.0
            shed_before = scheduler.shed_counts()["interactive"]
            for _ in range(28 if peak else 1):
                try:
                    scheduler.submit("inter", feats.take(16 if peak
                                                         else 8))
                except ServingOverloadedError:
                    pass
            if not peak:
                try:
                    scheduler.submit("bulk", feats.take(16))
                except ServingOverloadedError:
                    pass
            if controller is not None:
                controller.tick()    # samples the queued state
                state["chips"] = len(store.current().serving_chips())
            chips = state["chips"]
            chips_seen.add(chips)
            # budgeted inline drain: the tick's capacity in fake time
            while clock.t < t_end:
                formed = scheduler._next_batch(timeout=0.0)
                if formed is None:
                    break
                scheduler._dispatch(*formed)
            busy = clock.t - t0
            idle_sum += chips * max(0.0, 1.0 - busy / dt) / total_chips
            shed_now = (scheduler.shed_counts()["interactive"]
                        - shed_before)
            sheds += shed_now
            if shed_now or len(inter.pending) > 0:
                violation_min += dt / 60.0
            coord.poll()             # resizes apply at the boundary seam
            if coord.fleet_size >= 1:
                learner_last = clock.t
            max_stale = max(max_stale, clock.t - learner_last)
            if clock.t < t_end:
                clock.advance(t_end - clock.t)
        out = {
            "slo_violation_minutes": round(violation_min, 1),
            "chip_idle_fraction": round(idle_sum / ticks, 4),
            "interactive_sheds": sheds,
            "max_learner_staleness_s": round(max_stale, 1),
            "serving_chips_range": [min(chips_seen), max(chips_seen)],
        }
        if controller is not None:
            snap = controller.snapshot()
            out["decisions"] = snap["ticks"]
            out["actuations"] = snap["actuations"]
            out["generations"] = store.generation
        return out

    try:
        ctl = replay(controlled=True)
        static = replay(controlled=False)
        for key in ("slo_violation_minutes", "chip_idle_fraction",
                    "interactive_sheds", "max_learner_staleness_s",
                    "serving_chips_range"):
            a[key] = {"controller": ctl[key], "static": static[key]}
        a["controller_decisions"] = ctl["decisions"]
        a["controller_actuations"] = ctl["actuations"]
        a["placement_generations"] = ctl["generations"]
        better = (
            (ctl["slo_violation_minutes"] < static["slo_violation_minutes"])
            + (ctl["chip_idle_fraction"] < static["chip_idle_fraction"]))
        worse = (
            (ctl["slo_violation_minutes"] > static["slo_violation_minutes"])
            + (ctl["chip_idle_fraction"] > static["chip_idle_fraction"]))
        a["controller_dominates"] = bool(better >= 1 and worse == 0)
        results["autoscale_slo_violation_minutes"] = \
            ctl["slo_violation_minutes"]
        results["autoscale_idle_fraction"] = ctl["chip_idle_fraction"]
        results["autoscale_controller_dominates"] = \
            a["controller_dominates"]
    except Exception as exc:   # noqa: BLE001 — nulls stay null
        a["autoscale_error"] = repr(exc)[:300]


def bench_wal(results: dict) -> None:
    """Write-ahead window log durability cost (VERDICT r3 weak #7): live
    windows/s through the full per-window fsync pair, host-side only
    (~0.3 s).  r4 measurement: ~1100 w/s on the single-core bench host —
    far above any realistic online window rate, so the per-window fsync
    stays un-batched (data/wal.py module doc)."""
    import tempfile
    import time as _time

    from flink_ml_tpu import Table
    from flink_ml_tpu.data.wal import WindowLog

    host_rng = np.random.default_rng(11)
    xs = host_rng.normal(size=(256, 16)).astype(np.float32)
    src = (Table({"x": xs, "y": np.ones(256, np.float32)})
           for _ in range(300))
    with tempfile.TemporaryDirectory() as td:
        it = iter(WindowLog(src, td))
        next(it)  # warm (dir creation, first compile-free write)
        t0 = _time.perf_counter()
        n = sum(1 for _ in it)
        dt = _time.perf_counter() - t0
    results["notes"]["wal_windows_per_sec"] = round(n / dt, 1)


def bench_kernels(results: dict) -> None:
    """Kernel-registry leg (kernel_metric_version 1, ISSUE 10): the
    unified dispatch surface and the three registered hot paths, each as
    a within-run A/B against the path it replaced.

    - ``dispatch``: per-call cost of a registry dispatch (shared
      plan-static jit + compile/cache accounting) vs a bare module jit
      of the same margins expression — the refactor's overhead budget.
    - ``widedeep_routed_grad``: kernel-granularity step of the routed
      table gradient vs the autodiff-style scatter-add oracle (the
      CPU-smoke proxy for the targeted kernel), plus the fused Mosaic
      fold measured on TPU only with the fold's HBM-bytes accounting
      always present (the fused win is HBM traffic — TPU-only by
      construction, which the accounting states).
    - ``gbt_hist``: MXU double-one-hot histograms vs segment_sum at the
      same shape (both run anywhere; the MXU win needs a systolic
      array, so the CPU number is honest but expected < 1x).
    - ``kmeans_workset_fused``: fused workset assign+update vs the
      two-kernel XLA scoring+stats path; measured on TPU only, analytic
      HBM accounting always present.

    Measured fields are null, never faked, where a backend cannot
    honestly produce them; every sub-leg's analytic accounting is
    always published."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.kernels import registry as kreg

    smoke = _smoke()
    notes = results["notes"]
    notes["kernel_metric_version"] = 1
    kern = notes["kernels"] = {
        # pre-nulled headline fields: a mid-sub-leg crash keeps what was
        # already measured, nulls never become fake numbers
        "dispatch": {"registry_us": None, "direct_jit_us": None,
                     "overhead_us": None},
        "widedeep_routed_grad": {"scatter_add_ms": None,
                                 "routed_xla_ms": None,
                                 "routed_speedup": None,
                                 "fused_fold_ms": None,
                                 "fused_vs_xla": None,
                                 "accounting": None},
        "gbt_hist": {"segsum_ms": None, "mxu_ms": None,
                     "mxu_speedup": None, "accounting": None},
        "kmeans_workset_fused": {"two_kernel_ms": None, "fused_ms": None,
                                 "fused_speedup": None,
                                 "accounting": None},
        "registry": None,
    }

    def timed(fn, iters):
        fn()                                   # compile + warm
        best = None
        for _ in range(3):                     # best-of-3: one-off GC /
            t0 = time.perf_counter()           # background-compile spikes
            for _ in range(iters):             # must not skew an A/B leg
                out = fn()
            jax.tree_util.tree_map(
                lambda x: x.block_until_ready()
                if hasattr(x, "block_until_ready") else x, out)
            dt = (time.perf_counter() - t0) / iters
            best = dt if best is None else min(best, dt)
        return best

    # -- dispatch overhead A/B ---------------------------------------------
    from flink_ml_tpu.models.common.linear import (_jit_margins,
                                                   _linear_chain_kernel)

    rng = np.random.default_rng(41)
    # HOST arrays on purpose: the shared plan-jit DONATES the cols dict
    # on TPU, so a reused device array would be deleted after the first
    # dispatch — each call transfers (and donates) a fresh buffer, and
    # the direct-jit side gets the same host array so the A/B stays a
    # fair per-call comparison including the transfer.
    Xh = rng.normal(size=(256, 64)).astype(np.float32)
    wd = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))
    plan = ((_linear_chain_kernel, ("f", "m")),)
    params = ({"w": wd, "b": np.float32(0.1)},)
    iters = 50 if smoke else 200
    reg_s = timed(lambda: kreg.dispatch(plan, params, {"f": Xh},
                                        op="bench_dispatch")["m"], iters)
    jit_s = timed(lambda: _jit_margins(Xh, wd, np.float32(0.1)), iters)
    kern["dispatch"] = {
        "registry_us": round(reg_s * 1e6, 2),
        "direct_jit_us": round(jit_s * 1e6, 2),
        "overhead_us": round((reg_s - jit_s) * 1e6, 2),
    }

    # -- WideDeep routed-grad kernel A/B -----------------------------------
    from flink_ml_tpu.ops.emb_grad import emb_grad_route

    batch, fields, E = (2048 if smoke else 8192), 26, 16
    vocab = (1 << 14) if smoke else (1 << 20)
    cat = rng.integers(0, vocab, size=(1, batch, fields))
    cat[0, : batch // 2, 0] = 7          # heavy hitter -> deep fold
    route = emb_grad_route(cat, vocab)
    S = batch * fields
    g_flat = jnp.asarray(rng.normal(size=(S, E)).astype(np.float32))
    ids_flat = jnp.asarray(cat[0].reshape(-1).astype(np.int32))

    @jax.jit
    def scatter_oracle(g, ids):
        return jnp.zeros((vocab, E), jnp.float32).at[ids].add(g)

    step = route.step_slice(0)
    routed = jax.jit(lambda g: route.apply(g, *step))
    scat_s = timed(lambda: scatter_oracle(g_flat, ids_flat), 10)
    routed_s = timed(lambda: routed(g_flat), 10)
    fold_bytes = S * E * 4
    acct = {
        # the fused fold's case: unfused = one read+write of (S, E) per
        # fold pass; fused = one read + one write total.  Pure HBM
        # traffic — there is no FLOP win, so the speedup only exists on
        # a device where the fold is bandwidth-bound (TPU), which is why
        # the measured field is TPU-only.
        "fold_passes": route.fold_passes,
        "fold_hbm_bytes_xla": 2 * fold_bytes * max(route.fold_passes, 1),
        "fold_hbm_bytes_fused": 2 * fold_bytes,
        "fold_traffic_ratio": round(max(route.fold_passes, 1), 2),
        "note": ("the routed path trades random HBM read-modify-writes "
                 "for streaming passes + extra FLOPs; a CPU has cheap "
                 "random access and expensive FLOPs, so the CPU proxy "
                 "measures the inflated side (r4 measured the TPU win: "
                 "routed 9.4->~2 ms of the 18.8 ms step).  The fused "
                 "fold's own win is fold_traffic_ratio fewer HBM round "
                 "trips — pure bandwidth, TPU-only by construction"),
    }
    wd_leg = kern["widedeep_routed_grad"]
    wd_leg.update({
        "scatter_add_ms": round(scat_s * 1e3, 3),
        "routed_xla_ms": round(routed_s * 1e3, 3),
        "routed_speedup": round(scat_s / routed_s, 2),
        "accounting": acct,
    })
    if not smoke:
        from flink_ml_tpu.ops.emb_grad_pallas import (
            fold_block_n, routed_table_grad_gather_fused)

        bn = fold_block_n(S, route.fold_passes)
        if bn is not None:
            fused = jax.jit(lambda g: routed_table_grad_gather_fused(
                g, *step, fold_passes=route.fold_passes, block_n=bn))
            fused_s = timed(lambda: fused(g_flat), 10)
            wd_leg["fused_fold_ms"] = round(fused_s * 1e3, 3)
            wd_leg["fused_vs_xla"] = round(routed_s / fused_s, 2)

    # -- GBT histogram A/B --------------------------------------------------
    from flink_ml_tpu.models.common import gbt as gbt_mod

    hn, hd, hbins, hnodes = (1 << 14 if smoke else 1 << 18), 16, 64, 8
    binned = jnp.asarray(rng.integers(0, hbins, size=(hn, hd)), jnp.int32)
    ids = jnp.asarray(rng.integers(-1, hnodes, size=hn), jnp.int32)
    gh = jnp.asarray(rng.normal(size=hn).astype(np.float32))
    hh = jnp.asarray((rng.random(hn) + 0.1).astype(np.float32))
    seg_s = timed(lambda: gbt_mod._level_histograms_segsum(
        binned, ids, gh, hh, hnodes, hd, hbins), 5)
    mxu_s = timed(lambda: gbt_mod._level_histograms_mxu(
        binned, ids, gh, hh, hnodes, hd, hbins), 5)
    kern["gbt_hist"] = {
        "segsum_ms": round(seg_s * 1e3, 3),
        "mxu_ms": round(mxu_s * 1e3, 3),
        "mxu_speedup": round(seg_s / mxu_s, 2),
        "accounting": {
            "shape": f"{hn}x{hd}, {hnodes} nodes, {hbins} bins",
            # segsum: one random scatter-add per (row, feature) key;
            # mxu: 2*n*nodes*bins MAC per feature/value — trades random
            # HBM transactions for systolic-array throughput, so the
            # win needs an MXU (CPU measures the FLOP-inflated side)
            "segsum_scatter_ops": hn * hd * 2,
            "mxu_macs": 2 * hn * hnodes * hbins * hd * 2,
            "note": ("mxu trades per-element random accumulation for "
                     "dense one-hot matmuls — the win scales with "
                     "systolic-array throughput, so the registry only "
                     "defaults to it on TPU"),
        },
    }

    # -- fused KMeans workset assign+update A/B -----------------------------
    from flink_ml_tpu.models.clustering.kmeans import (
        kmeans_workset_update_xla)
    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.ops import kmeans_pallas as kp

    kn, kd, kk = (1 << 14 if smoke else 1 << 20), 32, 64
    pts = jnp.asarray(rng.normal(size=(kn, kd)).astype(np.float32))
    cents = pts[:kk]
    prev = jnp.zeros((kn,), jnp.int32)
    act = jnp.ones((kn,), jnp.float32)
    pm = jnp.ones((kn,), jnp.float32)
    measure = DistanceMeasure.get_instance("euclidean")
    two_kernel = jax.jit(lambda p, c: kmeans_workset_update_xla(
        measure, kk, p, c, prev, act, pm), static_argnums=())
    two_s = timed(lambda: two_kernel(pts, cents), 5)
    ws_acct = {
        # XLA path writes+reads the (n, k) distance matrix and the
        # (n, k) one-hot between scoring and the stats einsum; the fused
        # kernel keeps both in VMEM — points are read once, outputs are
        # O(n + k*d).  HBM-bound => TPU-only win, hence the null
        # measured field off TPU.
        "hbm_bytes_two_kernel": 2 * kn * kk * 4 * 2 + kn * kd * 4,
        "hbm_bytes_fused": kn * kd * 4 + kn * 12 + kk * kd * 4,
    }
    ws_leg = kern["kmeans_workset_fused"]
    ws_leg.update({"two_kernel_ms": round(two_s * 1e3, 3),
                   "accounting": ws_acct})
    if not smoke:
        bn = kp.pick_block_n_workset(kn, kd, kk)
        if bn is not None:
            fused_ws = jax.jit(lambda p, c: kp.kmeans_workset_update(
                p, c, prev, act, pm, block_n=bn))
            fws_s = timed(lambda: fused_ws(pts, cents), 5)
            ws_leg["fused_ms"] = round(fws_s * 1e3, 3)
            ws_leg["fused_speedup"] = round(two_s / fws_s, 2)

    # -- registry observability (the satellite's measured number) -----------
    snap = kreg.kernel_stats.snapshot()
    snap["per_op"] = {k: v for k, v in sorted(snap["per_op"].items())[:12]}
    kern["registry"] = snap


_COLDSTART_CHILD = '''
import json, os, time
import numpy as np
from jax._src import test_util as jtu
from flink_ml_tpu import Table
from flink_ml_tpu.models.classification.logisticregression import (
    LogisticRegressionModel)
from flink_ml_tpu.models.clustering.kmeans import KMeansModel
from flink_ml_tpu.models.common.gbt import GBTConfig, train_forest
from flink_ml_tpu.serving import ModelRegistry
from flink_ml_tpu.kernels.registry import kernel_stats

rng = np.random.default_rng(3)
d = 32
lr = LogisticRegressionModel()
lr.set_model_data(Table({"coefficients": rng.normal(size=(1, d)),
                         "intercept": np.array([0.2])}))
km = KMeansModel()
km.set_model_data(Table({
    "centroids": rng.normal(size=(8, d)).astype(np.float32)[None]}))
feats = Table({"features": rng.normal(size=(256, d)).astype(np.float32)})

registry = ModelRegistry()
t0 = time.perf_counter()
with jtu.count_jit_and_pmap_lowerings() as count:
    dep_lr = registry.deploy("lr", lr, feats.take(2), max_batch_rows=256)
    dep_km = registry.deploy("km", km, feats.take(2), max_batch_rows=256)
warmup_s = time.perf_counter() - t0

X = rng.normal(size=(4096, 8)).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float64)
def grad_hess(y, pred):
    p = 1.0 / (1.0 + np.exp(-pred))
    return (p - y), np.maximum(p * (1.0 - p), 1e-16)
t0 = time.perf_counter()
train_forest(X, y, grad_hess, 0.0,
             GBTConfig(num_trees=2, max_depth=4, max_bins=32))
gbt_s = time.perf_counter() - t0

snap = kernel_stats.snapshot()
print(json.dumps({
    "warmup_s": round(warmup_s, 4),
    "warmup_lowerings": count[0],
    "gbt_s": round(gbt_s, 4),
    "aot": snap["aot"],
    "reports": {"lr": dep_lr.servable.warmup_report,
                "km": dep_km.servable.warmup_report},
}))
'''


def bench_coldstart(results: dict) -> None:
    """Cold-start leg (coldstart_metric_version 1, ISSUE 12): the AOT
    executable cache's reason to exist, measured as a cold-vs-warm
    PROCESS A/B.  Two identical subprocesses deploy the serving op set
    (LR + KMeans bucketed servables) and pay GBT's training compile leg
    against one shared cache dir: the first compiles and persists, the
    second must warm up from deserialized executables — wall ratio is
    the headline, and the second process's lowering counter is the
    zero-compile evidence.  Children run on CPU always (the parent owns
    any TPU, and the acceptance series is the CPU-smoke op set — noted);
    the autotune sub-leg measures the histogram-backend search cost vs
    its steady-state win on this host.  Measured fields are null, never
    faked, when a sub-leg fails."""
    import subprocess
    import sys
    import tempfile

    cold = {
        "coldstart_metric_version": 1,
        # pre-nulled headline fields: a failed sub-leg keeps what was
        # measured, nulls never become fake numbers
        "cold_warmup_s": None, "warm_warmup_s": None,
        "coldstart_speedup": None, "warm_zero_lowerings": None,
        "gbt_compile_cold_s": None, "gbt_compile_warm_s": None,
        "gbt_compile_speedup": None,
        "aot_cold": None, "aot_warm": None, "warm_buckets": None,
        "autotune": {"winner": None, "search_ms": None,
                     "timings_ms": None, "steady_win_us_per_call": None},
        "note": ("children pinned to JAX_PLATFORMS=cpu: the parent owns "
                 "the accelerator, and the acceptance series is the "
                 "CPU-smoke serving op set (compile cost is host-side "
                 "either way)"),
    }
    results["coldstart_warm_speedup"] = None
    results["notes"]["coldstart"] = cold

    with tempfile.TemporaryDirectory(prefix="bench_aot_") as tmp:
        script = os.path.join(tmp, "coldstart_child.py")
        with open(script, "w") as f:
            f.write(_COLDSTART_CHILD)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["FLINK_ML_TPU_AOT_CACHE_PATH"] = os.path.join(tmp, "cache")
        env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__))
                             + os.pathsep + env.get("PYTHONPATH", ""))

        def run_child():
            proc = subprocess.run([sys.executable, script], env=env,
                                  capture_output=True, text=True,
                                  timeout=420)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"coldstart child failed: {proc.stderr[-400:]}")
            return json.loads(proc.stdout.strip().splitlines()[-1])

        first = run_child()
        second = run_child()
        cold["cold_warmup_s"] = first["warmup_s"]
        cold["warm_warmup_s"] = second["warmup_s"]
        cold["coldstart_speedup"] = round(
            first["warmup_s"] / max(second["warmup_s"], 1e-9), 2)
        cold["warm_zero_lowerings"] = second["warmup_lowerings"] == 0
        cold["gbt_compile_cold_s"] = first["gbt_s"]
        cold["gbt_compile_warm_s"] = second["gbt_s"]
        cold["gbt_compile_speedup"] = round(
            first["gbt_s"] / max(second["gbt_s"], 1e-9), 2)
        cold["aot_cold"] = first["aot"]
        cold["aot_warm"] = second["aot"]
        cold["warm_buckets"] = {
            name: {str(b): rec["source"]
                   for b, rec in rep["buckets"].items()}
            for name, rep in second["reports"].items()}
        results["coldstart_warm_speedup"] = cold["coldstart_speedup"]

    # -- autotune sub-leg: search cost vs steady-state win -------------------
    # both histogram impls are plain XLA programs, so the search runs
    # honestly on any backend; what the winner IS depends on the chip
    # (MXU wins on TPU) — the decision files record the device
    import jax.numpy as jnp

    from flink_ml_tpu.kernels import autotune
    from flink_ml_tpu.models.common import gbt as gbt_mod

    rng = np.random.default_rng(47)
    hn, hd, hbins, hnodes = 1 << 13, 16, 64, 8
    binned = jnp.asarray(rng.integers(0, hbins, size=(hn, hd)), jnp.int32)
    ids = jnp.asarray(rng.integers(-1, hnodes, size=hn), jnp.int32)
    g = jnp.asarray(rng.normal(size=hn).astype(np.float32))
    h = jnp.asarray((rng.random(hn) + 0.1).astype(np.float32))
    cands = {
        "segsum": lambda: gbt_mod._level_histograms_segsum(
            binned, ids, g, h, hnodes, hd, hbins),
        "mxu": lambda: gbt_mod._level_histograms_mxu(
            binned, ids, g, h, hnodes, hd, hbins),
    }
    t0 = time.perf_counter()
    timings = autotune.measure(cands)
    search_ms = (time.perf_counter() - t0) * 1e3
    winner = min(timings, key=timings.get)
    loser = max(timings, key=timings.get)
    cold["autotune"] = {
        "winner": winner,
        "search_ms": round(search_ms, 1),
        "timings_ms": {k: round(v, 3) for k, v in timings.items()},
        # what each later call banks by riding the measured choice
        # instead of the losing candidate — the search amortizes after
        # search_ms / win_per_call calls, and the persisted decision
        # makes that a ONE-TIME cost per fleet, not per process
        "steady_win_us_per_call": round(
            (timings[loser] - timings[winner]) * 1e3, 2),
        "probe": f"{hn}x{hd}, {hnodes} nodes, {hbins} bins",
    }


def bench_obs(results: dict) -> None:
    """Observability-overhead leg (obs_metric_version 1, ISSUE 13): is
    the unified tracing/probe layer off-the-hot-path cheap?  Two A/Bs,
    both within-run (the phase-independent ratio discipline):

    - **Serving**: the PR 2 64-client sweep against one warmed LR
      endpoint, tracing DISABLED then ENABLED — p99 and req/s both
      ways, the overhead fractions as the headline, and the XLA
      lowering counter across the enabled pass (MUST be 0: tracing is
      host bookkeeping, it never touches a compiled program).
    - **Chunked fit**: a dense streaming ``sgd_fit_outofcore`` at W=8,
      StepProbe detached then attached — per-step time from the
      post-compile epochs (``stream_info["epoch_seconds"][1:]``), so
      the ratio isolates the probe's carry + one-fetch-per-chunk cost.

    Plus the export surfaces exercised for real: span counts, a
    Chrome-trace file written and re-parsed, and the Prometheus
    exposition line count off the endpoint's metrics tree.  Measured
    fields are null, never faked, when a sub-leg fails."""
    import tempfile
    import threading

    from jax._src import test_util as jtu

    from flink_ml_tpu import Table
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)
    from flink_ml_tpu.obs import default_tree, prometheus_text
    from flink_ml_tpu.obs.trace import tracer
    from flink_ml_tpu.serving import ModelRegistry, ServingEndpoint

    obs: dict = {
        "obs_metric_version": 1,
        "serving_p99_ms_off": None, "serving_p99_ms_on": None,
        "serving_rps_off": None, "serving_rps_on": None,
        "tracing_p99_overhead_frac": None,
        "tracing_rps_overhead_frac": None,
        "tracing_new_lowerings": None,
        "spans_captured": None, "trace_export_events": None,
        "prometheus_lines": None,
        "probe_step_ms_off": None, "probe_step_ms_on": None,
        "probe_overhead_frac": None,
    }
    results["notes"]["obs"] = obs
    results.setdefault("obs_tracing_overhead_frac", None)

    # -- serving A/B ---------------------------------------------------------
    d = 64
    rng = np.random.default_rng(23)
    model = LogisticRegressionModel()
    model.set_model_data(Table({
        "coefficients": rng.normal(size=(1, d)),
        "intercept": np.array([0.1])}))
    feats = Table({"features": rng.normal(size=(1024, d))
                   .astype(np.float32)})
    registry = ModelRegistry()
    registry.deploy("lr", model, feats.take(2), max_batch_rows=256)
    endpoint = ServingEndpoint(registry, "lr", max_batch_rows=256,
                               max_wait_ms=1.0,
                               queue_capacity=1 << 14).start()

    def sweep(clients=64, per_client=16):
        latencies: list = []
        errors: list = []
        lock = threading.Lock()

        def client(worker):
            crng = np.random.default_rng(worker)
            mine = []
            try:
                for _ in range(per_client):
                    start = int(crng.integers(0, 1000))
                    rows = int(crng.integers(1, 9))
                    req = feats.slice(start, start + rows)
                    t0 = time.perf_counter()
                    endpoint.predict(req, timeout=120)
                    mine.append(time.perf_counter() - t0)
            except Exception as exc:   # noqa: BLE001 — surfaced below
                with lock:
                    errors.append(repr(exc)[:200])
            with lock:
                latencies.extend(mine)

        wall_t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        wall = time.perf_counter() - wall_t0
        if errors:
            # a failed client shrinks the sample: the A/B would compare
            # different populations — null the leg instead of skewing it
            raise RuntimeError(
                f"serving sweep lost {len(errors)} client(s): {errors[:3]}")
        lat = np.asarray(latencies)
        return (round(1e3 * float(np.quantile(lat, 0.99)), 3),
                round(len(lat) / wall, 1))

    try:
        sweep(clients=8, per_client=8)            # warm both paths
        p99_off, rps_off = sweep()
        tracer.enable()
        with jtu.count_jit_and_pmap_lowerings() as count:
            p99_on, rps_on = sweep()
        obs["tracing_new_lowerings"] = int(count[0])
        obs["spans_captured"] = tracer.count
        # export surfaces, exercised for real
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "trace.json")
            tracer.export_chrome(path)
            obs["trace_export_events"] = len(
                json.load(open(path))["traceEvents"])
        tree = default_tree(endpoint=endpoint, tracer=tracer)
        obs["prometheus_lines"] = len(
            prometheus_text(tree.snapshot()).strip().split("\n"))
        tracer.disable()
        tracer.clear()
        obs["serving_p99_ms_off"], obs["serving_rps_off"] = p99_off, rps_off
        obs["serving_p99_ms_on"], obs["serving_rps_on"] = p99_on, rps_on
        obs["tracing_p99_overhead_frac"] = round(p99_on / p99_off - 1, 4)
        obs["tracing_rps_overhead_frac"] = round(1 - rps_on / rps_off, 4)
        results["obs_tracing_overhead_frac"] = \
            obs["tracing_p99_overhead_frac"]
    finally:
        tracer.disable()
        endpoint.close()

    # -- chunked-fit A/B -----------------------------------------------------
    from flink_ml_tpu.models.common.losses import squared_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    steps, batch, fd = 32, 256, 32
    coefs = np.arange(1, fd + 1, dtype=np.float32)

    def mk():
        frng = np.random.default_rng(11)

        def make_reader():
            for _ in range(steps):
                X = frng.normal(size=(batch, fd)).astype(np.float32)
                yield {"features": X, "label": X @ coefs}

        return make_reader

    cfg = SGDConfig(max_epochs=3, tol=0.0)

    def fit_step_ms(probe: bool):
        info: dict = {}
        sgd_fit_outofcore(squared_loss, mk(), num_features=fd, config=cfg,
                          steps_per_dispatch=8, stream_info=info,
                          cache_decoded=False, step_probe=probe)
        # epoch 0 pays the compile; post-compile epochs are the signal
        return min(info["epoch_seconds"][1:]) * 1e3 / steps

    try:
        obs["probe_step_ms_off"] = round(fit_step_ms(False), 4)
        obs["probe_step_ms_on"] = round(fit_step_ms(True), 4)
        obs["probe_overhead_frac"] = round(
            obs["probe_step_ms_on"] / obs["probe_step_ms_off"] - 1, 4)
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        obs["probe_error"] = repr(exc)[:200]


def bench_multitenant(results: dict) -> None:
    """Multi-tenant serving leg (multitenant_metric_version 1, ISSUE 14):
    the shared scheduler under contention, closed-loop with a zipfian
    tenant/key mix and a diurnal bulk ramp.  Within-run A/Bs (the
    phase-independent ratio discipline), every variant compiled+warmed
    before either is timed:

    - **Contention**: interactive-class p99 alone vs with 8 contending
      bulk tenants on the same scheduler (headline ratio; acceptance
      <= 2x), vs the same interleaved traffic through one unbounded
      FIFO endpoint (no classes, no WFQ — what the ratio is measured
      against).
    - **Admission**: tenants 2..9 share tenant 1's schema — the
      admission must be compilation-free (warm-up source attribution
      summed, plus the XLA lowering counter across the LAST admission).
    - **Shed order**: a small-capacity scheduler under interleaved
      overload — sheds must be 100% bulk-class before any interactive
      shed.
    - **Publish isolation**: tenant B's p99 while tenant A takes
      continuous delta publishes vs while it doesn't (the PR 7 chaos
      target: ratio within run-to-run noise), with zero dropped
      requests.
    - **Embedding cache**: WideDeep zipfian key mix through the
      device-resident row-block cache — hit rate headline (acceptance
      > 0.8 on the zipfian mix).
    - **Shed fast path**: the lock-free overload check A/B (4 threads
      hammering a saturated queue, fast path on vs off) — the
      MicroBatcher satellite's evidence.

    Measured fields are null, never faked, when a sub-leg fails."""
    import threading

    from jax._src import test_util as jtu

    from flink_ml_tpu import Table
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)
    from flink_ml_tpu.serving import (MicroBatcher, ModelRegistry,
                                      ServingEndpoint,
                                      ServingOverloadedError,
                                      SharedScheduler, make_servable)

    mt: dict = {
        "multitenant_metric_version": 1,
        "config": "LR d=32 x 9 tenants (1 interactive + 8 bulk, zipfian "
                  "mix), max_batch_rows=128, bulk cap 8, max_wait_ms=0.5; WideDeep "
                  "vocab 4096+1024, block_rows=64",
        "p99_interactive_alone_ms": None,
        "p99_interactive_contended_ms": None,
        "p99_interactive_fifo_ms": None,
        "fifo_vs_scheduler_ratio": None,
        "fifo_interactive_sheds": None,
        "admit_compiles_tenant1": None,
        "admit_compiles_tenants_2_to_9": None,
        "admit_zero_lowerings": None,
        "shed_counts": None,
        "publish_p99_before_ms": None,
        "publish_p99_during_ms": None,
        "publishes_during": None,
        "publish_dropped_requests": None,
        "emb_cache": None,
        "shed_fastpath": None,
        "ramp": None,
    }
    results["notes"]["multitenant"] = mt
    # headline fields: pre-nulled at leg entry, never faked
    results.setdefault("multitenant_contended_p99_ratio", None)
    results.setdefault("multitenant_shed_bulk_only", None)
    results.setdefault("multitenant_publish_p99_ratio", None)
    results.setdefault("emb_cache_hit_rate", None)

    d = 32
    rng = np.random.default_rng(41)

    def lr_model(seed):
        m = LogisticRegressionModel()
        mrng = np.random.default_rng(seed)
        m.set_model_data(Table({
            "coefficients": mrng.normal(size=(1, d)),
            "intercept": np.array([0.1])}))
        return m

    feats = Table({"features": rng.normal(size=(1024, d))
                   .astype(np.float32)})

    import gc
    import sys

    # latency-sensitive serving tuning, both restored in the leg's
    # finally: (a) the default 5 ms GIL switch interval lets one flood
    # thread hold the interpreter for longer than the whole p99 budget
    # on a 1-core smoke host; (b) a gen-2 GC pause lands as a
    # multi-ms p99 outlier in whichever variant it happens to hit —
    # the same two knobs a real single-core serving deployment sets.
    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)
    mt["gil_switch_interval_s"] = 0.0005
    gc_was_enabled = gc.isenabled()
    gc.disable()

    # -- admission + contention on ONE scheduler -----------------------------
    # bulk_batch_rows=8: a dispatched batch is not preemptible, so the
    # bulk cap bounds the worst head-of-line block an interactive
    # arrival eats — 8 rows keeps it at a single bucket-8 dispatch at
    # this shape (swept 8-128; one bucket-8 request per bulk batch makes
    # the non-preemptible bulk quantum ~ one interactive service time)
    sched = SharedScheduler(max_batch_rows=128, max_wait_ms=0.5,
                            queue_capacity=1 << 13, bulk_batch_rows=8)
    try:
        t1 = sched.add_tenant("inter", lr_model(0), feats.take(2),
                              slo="interactive")
        mt["admit_compiles_tenant1"] = t1.admission_report["compiled"]
        later_compiles = 0
        for i in range(7):
            t = sched.add_tenant(f"bulk{i}", lr_model(i + 1),
                                 feats.take(2), slo="bulk")
            later_compiles += t.admission_report["compiled"]
        with jtu.count_jit_and_pmap_lowerings() as count:
            t9 = sched.add_tenant("bulk7", lr_model(8), feats.take(2),
                                  slo="bulk")
        later_compiles += t9.admission_report["compiled"]
        mt["admit_compiles_tenants_2_to_9"] = later_compiles
        mt["admit_zero_lowerings"] = int(count[0]) == 0
        sched.start()

        bulk_names = [f"bulk{i}" for i in range(8)]
        # zipfian tenant mix: bulk tenant i takes share ~ 1/(i+1)
        zipf_w = 1.0 / (np.arange(8) + 1.0)
        zipf_w /= zipf_w.sum()

        def interactive_load(n_clients=2, per_client=200,
                             samples=None):
            """Paced closed-loop interactive clients; returns p99 ms
            (and extends ``samples`` with the raw latencies when
            given — the pooled-pairs A/B below)."""
            latencies: list = []
            errors: list = []
            lock = threading.Lock()

            def client(worker):
                crng = np.random.default_rng(100 + worker)
                mine = []
                try:
                    for _ in range(per_client):
                        start = int(crng.integers(0, 1000))
                        rows = int(crng.integers(1, 5))
                        req = feats.slice(start, start + rows)
                        t0 = time.perf_counter()
                        sched.predict("inter", req, timeout=120)
                        mine.append(time.perf_counter() - t0)
                        # paced closed loop: a user clicking, not a
                        # saturating spin — keeps the p99 measuring
                        # the serving fabric instead of the client's
                        # own GIL self-queueing on the 1-core host
                        time.sleep(0.001)
                except Exception as exc:   # noqa: BLE001
                    with lock:
                        errors.append(repr(exc)[:200])
                with lock:
                    latencies.extend(mine)

            threads = [threading.Thread(target=client, args=(w,))
                       for w in range(n_clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            if errors:
                raise RuntimeError(f"interactive client lost: {errors[:3]}")
            if samples is not None:
                samples.extend(latencies)
            return round(1e3 * float(np.quantile(
                np.asarray(latencies), 0.99)), 3)

        def bulk_flood(stop, n_clients):
            """Open bulk load above service capacity: each client
            bursts 8-row requests at zipfian-picked tenants, sleeping
            only between bursts/sheds — the bulk queue saturates to its
            class threshold and STAYS there (sheds expected; the fast
            path makes them cheap).  All 8 bulk TENANTS stay backlogged
            from few flood threads — tenant-level contention without
            drowning the 1-core smoke host in GIL churn that would
            measure the OS scheduler instead of this one."""
            def client(worker):
                crng = np.random.default_rng(500 + worker)
                while not stop.is_set():
                    shed = False
                    for _ in range(4):
                        name = bulk_names[int(crng.choice(8, p=zipf_w))]
                        start = int(crng.integers(0, 900))
                        try:
                            sched.submit(name,
                                         feats.slice(start, start + 8))
                        except (ServingOverloadedError, RuntimeError):
                            shed = True
                    time.sleep(0.001 if shed else 0.0005)

            threads = [threading.Thread(target=client, args=(w,),
                                        daemon=True)
                       for w in range(n_clients)]
            for t in threads:
                t.start()
            return threads

        # warm every path both variants touch before ANY timing
        interactive_load(n_clients=2, per_client=8)
        stop = threading.Event()
        flood = bulk_flood(stop, 2)
        try:
            interactive_load(n_clients=2, per_client=8)
        finally:
            stop.set()
            for t in flood:
                t.join(10)

        ramp = []
        for phase, n_bulk in (("low", 1), ("high", 2)):   # diurnal ramp
            stop = threading.Event()
            flood = bulk_flood(stop, n_bulk)
            try:
                p99 = interactive_load(per_client=100)
            finally:
                stop.set()
                for t in flood:
                    t.join(10)
            ramp.append({"phase": phase, "bulk_clients": n_bulk,
                         "p99_interactive_ms": p99})
        mt["ramp"] = ramp

        # headline A/B: ALTERNATING alone/contended pairs — on a 1-core
        # smoke host a single scheduling hiccup lands as a p99 outlier
        # in whichever variant it hits; alternating and pooling is the
        # within-run discipline that survives it (the comm-leg
        # warm-both-then-time stance, extended)
        pairs = []
        alone_samples: list = []
        contended_samples: list = []
        for _ in range(4):
            alone = interactive_load(samples=alone_samples)
            stop = threading.Event()
            flood = bulk_flood(stop, 2)
            try:
                # settle: the flood's queue-FILL transient (no sheds
                # yet -> no shed-sleeps -> max submit churn) is not the
                # steady contention under measurement
                time.sleep(0.25)
                contended = interactive_load(samples=contended_samples)
            finally:
                stop.set()
                for t in flood:
                    t.join(10)
            pairs.append({"alone_ms": alone, "contended_ms": contended,
                          "ratio": round(contended / alone, 3)})
        mt["contention_pairs"] = pairs
        # the headline ratio comes from the POOLED samples (4 x 400 per
        # variant): a per-pair p99 is 4 samples from its tail, and a
        # ratio of two of those is OS-jitter noise on a 1-core host
        alone_p99 = round(1e3 * float(np.quantile(
            np.asarray(alone_samples), 0.99)), 3)
        contended_p99 = round(1e3 * float(np.quantile(
            np.asarray(contended_samples), 0.99)), 3)
        mt["p99_interactive_alone_ms"] = alone_p99
        mt["p99_interactive_contended_ms"] = contended_p99
        results["multitenant_contended_p99_ratio"] = round(
            contended_p99 / alone_p99, 3)

        # -- publish isolation: delta pushes to bulk0 while inter serves --
        publishes = [0]
        pub_errors: list = []

        def publisher(stop):
            # a realistic continuous-learning cadence (~50 publishes/s;
            # bench_online measures raw publish cost separately) — the
            # question here is whether tenant A's publishes move tenant
            # B's p99, not how fast the 1-core host can spin rebinds
            models = (lr_model(1), lr_model(101))
            try:
                while not stop.is_set():
                    live = sched.registry.current("bulk0")
                    nxt = models[(publishes[0] + 1) % 2]
                    sched.registry.publish_servable(
                        "bulk0", live.servable.rebind(nxt),
                        metrics=sched.tenant("bulk0").metrics,
                        mode="delta")
                    publishes[0] += 1
                    time.sleep(0.02)
            except Exception as exc:   # noqa: BLE001
                pub_errors.append(repr(exc)[:200])

        pub_pairs = []
        before_samples: list = []
        during_samples: list = []
        for _ in range(3):
            before = interactive_load(n_clients=2, per_client=100,
                                      samples=before_samples)
            stop = threading.Event()
            pub = threading.Thread(target=publisher, args=(stop,),
                                   daemon=True)
            pub.start()
            try:
                during = interactive_load(n_clients=2, per_client=100,
                                          samples=during_samples)
            finally:
                stop.set()
                pub.join(10)
            pub_pairs.append({"before_ms": before, "during_ms": during,
                              "ratio": round(during / before, 3)})
        if not pub_errors:
            mt["publish_pairs"] = pub_pairs
            before_p99 = round(1e3 * float(np.quantile(
                np.asarray(before_samples), 0.99)), 3)
            during_p99 = round(1e3 * float(np.quantile(
                np.asarray(during_samples), 0.99)), 3)
            mt["publish_p99_before_ms"] = before_p99
            mt["publish_p99_during_ms"] = during_p99
            mt["publishes_during"] = publishes[0]
            mt["publish_dropped_requests"] = 0   # interactive_load raises
            #                                      on any lost client
            results["multitenant_publish_p99_ratio"] = round(
                during_p99 / before_p99, 3)
        else:
            mt["publish_error"] = pub_errors[0]
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        mt["contention_error"] = repr(exc)[:200]
    finally:
        sched.close()

    # -- baseline topology: one endpoint per model, no coordination ----------
    # the topology the scheduler replaces (PR 2): every tenant owns an
    # endpoint with its own batcher, queue, and serve thread — nine
    # uncoordinated FIFO loops time-slicing one device with no classes,
    # no priorities, no cross-tenant fairness.  Same models, same
    # request streams as the scheduler's high phase.
    try:
        endpoints = {}
        for i, name in enumerate(["inter"] + bulk_names):
            reg = ModelRegistry()
            reg.deploy(name, lr_model(i), feats.take(2),
                       max_batch_rows=128)
            endpoints[name] = ServingEndpoint(
                reg, name, max_batch_rows=128, max_wait_ms=0.5,
                queue_capacity=4096).start()
        stop = threading.Event()
        try:
            def fifo_bulk(worker):
                crng = np.random.default_rng(900 + worker)
                while not stop.is_set():
                    shed = False
                    for _ in range(4):       # the bulk_flood burst shape
                        name = bulk_names[int(crng.choice(8, p=zipf_w))]
                        start = int(crng.integers(0, 900))
                        try:
                            endpoints[name].submit(
                                feats.slice(start, start + 8))
                        except (ServingOverloadedError, RuntimeError):
                            shed = True
                    time.sleep(0.001 if shed else 0.0005)

            fifo_sheds = [0]

            def fifo_interactive():
                latencies: list = []
                lock = threading.Lock()
                errors: list = []

                def client(worker):
                    crng = np.random.default_rng(100 + worker)
                    mine = []
                    try:
                        # an interactive request shed by ITS endpoint
                        # (per-endpoint FIFO has no cross-tenant view)
                        # retries until served; latency runs from the
                        # FIRST attempt — what the user waiting on the
                        # click experiences
                        for _ in range(50):
                            start = int(crng.integers(0, 1000))
                            rows = int(crng.integers(1, 5))
                            req = feats.slice(start, start + rows)
                            t0 = time.perf_counter()
                            while True:
                                try:
                                    endpoints["inter"].predict(
                                        req, timeout=120)
                                    break
                                except ServingOverloadedError:
                                    with lock:
                                        fifo_sheds[0] += 1
                                    time.sleep(0.002)
                            mine.append(time.perf_counter() - t0)
                            time.sleep(0.001)   # the same pacing as
                            #                     the scheduler sweep
                    except Exception as exc:   # noqa: BLE001
                        with lock:
                            errors.append(repr(exc)[:200])
                    with lock:
                        latencies.extend(mine)

                threads = [threading.Thread(target=client, args=(w,),
                                            daemon=True)
                           for w in range(2)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(300)
                if errors:
                    raise RuntimeError(f"fifo client lost: {errors[:3]}")
                return round(1e3 * float(np.quantile(
                    np.asarray(latencies), 0.99)), 3)

            fifo_interactive()                   # warm
            flood = [threading.Thread(target=fifo_bulk, args=(w,),
                                      daemon=True)
                     for w in range(2)]          # same load as the
            #                                      scheduler's high phase
            for t in flood:
                t.start()
            try:
                mt["p99_interactive_fifo_ms"] = fifo_interactive()
            finally:
                stop.set()
                for t in flood:
                    t.join(10)
            mt["fifo_interactive_sheds"] = fifo_sheds[0]
            if mt["p99_interactive_contended_ms"]:
                mt["fifo_vs_scheduler_ratio"] = round(
                    mt["p99_interactive_fifo_ms"]
                    / mt["p99_interactive_contended_ms"], 3)
        finally:
            stop.set()
            for ep in endpoints.values():
                ep.close()
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        mt["fifo_error"] = repr(exc)[:200]

    # -- shed order under interleaved overload -------------------------------
    try:
        small = SharedScheduler(max_batch_rows=64, queue_capacity=64)
        small.add_tenant("i", lr_model(0), feats.take(2),
                         slo="interactive")
        small.add_tenant("b", lr_model(1), feats.take(2), slo="bulk")
        # NOT started: pure admission against a filling queue (the
        # contract under test is shed ORDER, not drain throughput)
        crng = np.random.default_rng(77)
        shed_seq = []
        for _ in range(200):
            name = "b" if crng.random() < 0.6 else "i"
            try:
                small.submit(name, feats.take(1))
            except ServingOverloadedError:
                shed_seq.append(name)
        counts = small.shed_counts()
        mt["shed_counts"] = counts
        first_interactive_shed = (shed_seq.index("i")
                                  if "i" in shed_seq else None)
        bulk_before = (all(s == "b" for s in
                           shed_seq[:first_interactive_shed])
                       if first_interactive_shed is not None else True)
        results["multitenant_shed_bulk_only"] = bool(
            counts["bulk"] > 0 and bulk_before)
        small.close()
    except Exception as exc:   # noqa: BLE001
        mt["shed_error"] = repr(exc)[:200]

    # -- embedding-row cache on the zipfian key mix --------------------------
    try:
        from flink_ml_tpu.models.recommendation.widedeep import WideDeep

        vocab = (4096, 1024)
        n = 512
        wrng = np.random.default_rng(13)

        def zipf_ids(size, v, a=1.3):
            return ((wrng.zipf(a, size=size) - 1) % v).astype(np.int32)

        dense = wrng.normal(size=(n, 8)).astype(np.float32)
        cat = np.stack([zipf_ids(n, v) for v in vocab],
                       axis=1).astype(np.int32)
        label = (cat[:, 0] < 8).astype(np.int64)
        train = Table({"denseFeatures": dense, "catFeatures": cat,
                       "label": label})
        model = (WideDeep().set_vocab_sizes(list(vocab))
                 .set_max_iter(1).fit(train))
        servable = make_servable(
            model, train.drop("label").take(2), emb_cache=True,
            cache_block_rows=64, cache_capacity_blocks=20,
            max_batch_rows=64)
        servable.warm_up()
        cache = servable.cache
        cache.reset_counters()   # warm-up faults are not traffic
        for _ in range(200):
            rows = int(wrng.integers(1, 9))
            req = Table({
                "denseFeatures": wrng.normal(size=(rows, 8))
                .astype(np.float32),
                "catFeatures": np.stack(
                    [zipf_ids(rows, v) for v in vocab], axis=1)})
            servable.predict(req)
        snap = cache.snapshot()
        mt["emb_cache"] = snap
        results["emb_cache_hit_rate"] = snap["hit_rate"]
    except Exception as exc:   # noqa: BLE001
        mt["emb_cache_error"] = repr(exc)[:200]

    # -- shed fast-path A/B (MicroBatcher satellite) -------------------------
    try:
        def shed_wall(fast):
            batcher = MicroBatcher(max_batch_rows=8, queue_capacity=2)
            for _ in range(2):
                batcher.submit(feats.take(1))     # saturate
            batcher.fast_shed = fast
            per_thread = 4000
            barrier = threading.Barrier(4 + 1)

            def hammer():
                barrier.wait()
                req = feats.take(1)
                for _ in range(per_thread):
                    try:
                        batcher.submit(req)
                    except ServingOverloadedError:
                        pass

            threads = [threading.Thread(target=hammer) for _ in range(4)]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join(60)
            return time.perf_counter() - t0

        shed_wall(True)                            # warm both paths
        shed_wall(False)
        locked_s = shed_wall(False)
        fast_s = shed_wall(True)
        mt["shed_fastpath"] = {
            "locked_wall_s": round(locked_s, 4),
            "fastpath_wall_s": round(fast_s, 4),
            "speedup": round(locked_s / fast_s, 3),
            "sheds_per_variant": 4 * 4000,
        }
    except Exception as exc:   # noqa: BLE001
        mt["shed_fastpath_error"] = repr(exc)[:200]
    finally:
        sys.setswitchinterval(old_switch)
        if gc_was_enabled:
            gc.enable()
            gc.collect()


def bench_int8(results: dict) -> None:
    """Int8 serving leg (int8_metric_version 1, ISSUE 18): quantized
    inference as the models-per-chip multiplier.  Within-run A/Bs,
    every variant compiled+warmed before either is timed:

    - **Latency/throughput**: req/s and p99 through the shared
      scheduler, 4 same-schema LR tenants per variant, closed-loop
      client sweep (64 clients on TPU, scaled down for smoke) — f32 vs
      int8, alternating timed rounds, pooled samples.
    - **Headline (models-per-chip at fixed SLO)**: resident param
      bytes per model measured off the live servable's kernel pytree;
      models-per-chip = HBM budget // bytes-per-model, computed for a
      variant ONLY if its multi-tenant p99 met the fixed SLO — the
      multiplier is footprint, the SLO gate keeps it honest.
    - **Embedding cache at fixed pool bytes**: the int8 pools (codes +
      per-row scales) sized to the f32 variant's exact byte budget —
      resident-rows ratio (acceptance ~2x) and zipfian hit rate, both
      variants on the same key stream.

    Measured fields are null, never faked, when a sub-leg fails."""
    import threading

    from flink_ml_tpu import Table
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)
    from flink_ml_tpu.serving import EmbeddingRowCache, SharedScheduler

    smoke = _smoke()
    n_clients = 8 if smoke else 64
    per_client = 25 if smoke else 200
    n_tenants = 4
    d = 4096
    slo_p99_ms = 250.0 if smoke else 25.0
    hbm_budget = 8 * (1 << 30)     # params' share of a v5e's 16 GB HBM

    q: dict = {
        "int8_metric_version": 1,
        "config": f"LR d={d} x {n_tenants} same-schema tenants per "
                  f"variant, {n_clients} closed-loop clients x "
                  f"{per_client} reqs x 2 alternating rounds; SLO p99 "
                  f"<= {slo_p99_ms} ms; HBM params budget "
                  f"{hbm_budget >> 30} GiB; embcache vocab 4096 x 64, "
                  "block_rows=64, int8 pools sized to the f32 byte "
                  "budget",
        "f32": None,
        "int8": None,
        "slo_p99_ms": slo_p99_ms,
        "hbm_budget_bytes": hbm_budget,
        "models_per_chip_f32": None,
        "models_per_chip_int8": None,
        "embcache": None,
    }
    results["notes"]["int8"] = q
    # headline fields: pre-nulled at leg entry, never faked
    results.setdefault("int8_p99_ratio", None)
    results.setdefault("int8_models_per_chip_ratio", None)
    results.setdefault("int8_embcache_rows_ratio", None)

    rng = np.random.default_rng(51)
    feats = Table({"features": rng.normal(size=(1024, d))
                   .astype(np.float32)})

    def lr_model(seed):
        mrng = np.random.default_rng(seed)
        m = LogisticRegressionModel()
        m.set_model_data(Table({
            "coefficients": mrng.normal(size=(1, d)),
            "intercept": np.array([0.1])}))
        return m

    import gc
    import sys

    # the multitenant leg's documented serving tuning, restored on exit
    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)
    gc_was_enabled = gc.isenabled()
    gc.disable()

    # -- latency/throughput + resident bytes, f32 vs int8 --------------------
    scheds: dict = {}
    try:
        import jax

        stats = {"f32": {"samples": [], "reqs": 0, "wall_s": 0.0},
                 "int8": {"samples": [], "reqs": 0, "wall_s": 0.0}}
        for precision in ("f32", "int8"):
            kw = {} if precision == "f32" else {"precision": "int8"}
            sched = SharedScheduler(max_batch_rows=128, max_wait_ms=0.5,
                                    queue_capacity=1 << 12)
            for i in range(n_tenants):
                sched.add_tenant(f"t{i}", lr_model(i), feats.take(2),
                                 slo="interactive", **kw)
            sched.start()
            scheds[precision] = sched

        def load(precision, per, samples=None):
            """Paced closed-loop clients round-robin over the variant's
            tenants; returns (n_requests, wall_s)."""
            sched = scheds[precision]
            latencies: list = []
            errors: list = []
            lock = threading.Lock()

            def client(worker):
                crng = np.random.default_rng(300 + worker)
                mine = []
                try:
                    for _ in range(per):
                        start = int(crng.integers(0, 1000))
                        rows = int(crng.integers(1, 5))
                        req = feats.slice(start, start + rows)
                        t0 = time.perf_counter()
                        sched.predict(f"t{worker % n_tenants}", req,
                                      timeout=120)
                        mine.append(time.perf_counter() - t0)
                        time.sleep(0.001)
                except Exception as exc:   # noqa: BLE001
                    with lock:
                        errors.append(repr(exc)[:200])
                with lock:
                    latencies.extend(mine)

            threads = [threading.Thread(target=client, args=(w,))
                       for w in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            wall = time.perf_counter() - t0
            if errors:
                raise RuntimeError(f"{precision} client lost: "
                                   f"{errors[:3]}")
            if samples is not None:
                samples.extend(latencies)
            return len(latencies), wall

        for precision in ("f32", "int8"):     # warm every path first
            load(precision, 4)
        for _ in range(2):                    # alternating timed rounds
            for precision in ("f32", "int8"):
                n, wall = load(precision, per_client,
                               samples=stats[precision]["samples"])
                stats[precision]["reqs"] += n
                stats[precision]["wall_s"] += wall

        for precision in ("f32", "int8"):
            sv = scheds[precision].registry.current("t0").servable
            leaves = jax.tree_util.tree_leaves(sv._kernel.params)
            resident = int(sum(int(np.asarray(x).nbytes)
                               for x in leaves))
            samples = np.asarray(stats[precision]["samples"])
            p99 = round(1e3 * float(np.quantile(samples, 0.99)), 3)
            q[precision] = {
                "req_per_s": round(stats[precision]["reqs"]
                                   / stats[precision]["wall_s"], 1),
                "p99_ms": p99,
                "resident_param_bytes": resident,
            }
            # models-per-chip only counts for a variant that MET the
            # SLO on the multi-tenant sweep — a fast-but-missed or a
            # dense-but-met variant never fakes the multiplier
            if p99 <= slo_p99_ms:
                q[f"models_per_chip_{precision}"] = int(
                    hbm_budget // resident)
        results["int8_p99_ratio"] = round(
            q["int8"]["p99_ms"] / q["f32"]["p99_ms"], 3)
        if q["models_per_chip_f32"] and q["models_per_chip_int8"]:
            results["int8_models_per_chip_ratio"] = round(
                q["models_per_chip_int8"] / q["models_per_chip_f32"], 3)
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        q["sweep_error"] = repr(exc)[:200]
    finally:
        for sched in scheds.values():
            sched.close()
        sys.setswitchinterval(old_switch)
        if gc_was_enabled:
            gc.enable()
            gc.collect()

    # -- embedding cache: resident rows + hit rate at FIXED pool bytes -------
    try:
        V, E, B = 4096, 64, 64
        wrng = np.random.default_rng(13)
        emb = wrng.normal(size=(V, E)).astype(np.float32)
        cache_f = EmbeddingRowCache({"emb": emb}, block_rows=B,
                                    capacity_blocks=16)
        budget = cache_f.pool_bytes
        probe = EmbeddingRowCache({"emb": emb}, block_rows=B,
                                  capacity_blocks=1, precision="int8")
        cap_q = int(budget // probe.pool_bytes)
        cache_q = EmbeddingRowCache({"emb": emb}, block_rows=B,
                                    capacity_blocks=cap_q,
                                    precision="int8")
        assert cache_q.pool_bytes <= budget

        def zipf_traffic(cache, rounds=300):
            trng = np.random.default_rng(29)
            for _ in range(rounds):
                ids = ((trng.zipf(1.3, size=8) - 1) % V).astype(np.int32)
                cache.lookup(ids)
            return cache.snapshot()

        snap_f = zipf_traffic(cache_f)
        snap_q = zipf_traffic(cache_q)
        rows_f = snap_f["capacity_blocks"] * B
        rows_q = snap_q["capacity_blocks"] * B
        q["embcache"] = {
            "pool_budget_bytes": int(budget),
            "int8_pool_bytes": int(cache_q.pool_bytes),
            "f32": {"resident_rows": rows_f,
                    "hit_rate": snap_f["hit_rate"]},
            "int8": {"resident_rows": rows_q,
                     "hit_rate": snap_q["hit_rate"]},
        }
        results["int8_embcache_rows_ratio"] = round(rows_q / rows_f, 3)
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        q["embcache_error"] = repr(exc)[:200]


def bench_retrieval(results: dict) -> None:
    """Vector retrieval leg (retrieval_metric_version 1, ISSUE 19): the
    recall@QPS frontier of the fused IVF scan+top-k kernel.

    - **Frontier**: recall@10 vs QPS over an nprobe sweep, flat
      brute-force (direct jitted matmul+top_k over the whole corpus) vs
      IVF vs IVF-PQ, every variant compiled+warmed before timing; the
      headline ratio is the fastest IVF point that still clears
      recall@10 >= 0.95 while scanning <= 25% of the corpus, over the
      flat baseline (acceptance >= 3x on the CPU smoke corpus).
    - **Contention p99**: closed-loop multi-tenant client sweep (64
      clients on TPU, scaled down for smoke) over 4 same-schema index
      tenants on the shared scheduler.
    - **Publish latency**: steady-state insert deltas through the
      digest-verified codec vs same-size full republishes, medians.

    Measured fields are null, never faked, when a sub-leg fails."""
    import threading

    from flink_ml_tpu import Table
    from flink_ml_tpu.kernels.registry import lookup
    from flink_ml_tpu.retrieval import (
        IVFIndex,
        PQConfig,
        exact_neighbors,
        recall_at_k,
    )
    from flink_ml_tpu.retrieval.ivf import _NN_STAGE
    from flink_ml_tpu.serving import SharedScheduler

    smoke = _smoke()
    n = 65536 if smoke else 131072
    d = 64
    nlist = 256
    per_mass = 32                      # points per natural micro-cluster
    k = 10
    nq = 256
    rounds = 3 if smoke else 10
    n_clients = 8 if smoke else 64
    per_client = 25 if smoke else 200
    n_tenants = 4
    ref_nprobe = 2

    q: dict = {
        "retrieval_metric_version": 1,
        "config": f"micro-cluster corpus n={n} d={d} ({n // per_mass} "
                  f"masses x {per_mass}), nlist={nlist}, k={k}, {nq} "
                  f"queries x {rounds} timed rounds per frontier point "
                  f"(reference nprobe {ref_nprobe}); contention "
                  f"{n_clients} closed-loop clients x {per_client} reqs "
                  f"over {n_tenants} same-schema index tenants; publish "
                  "medians over insert deltas vs full republishes",
        "frontier": None,
        "contention": None,
        "publish": None,
    }
    results["notes"]["retrieval"] = q
    # headline fields: pre-nulled at leg entry, never faked
    results.setdefault("retrieval_ivf_qps_ratio", None)
    results.setdefault("retrieval_recall_at_10", None)
    results.setdefault("retrieval_contention_p99_ms", None)
    results.setdefault("retrieval_publish_delta_vs_full_ratio", None)

    # Many small, tight, well-separated masses: the regime where an IVF
    # index genuinely earns its keep — each query's whole top-10 lives
    # inside one mass, so a couple of probes recover recall ~1 while
    # scanning ~1% of the corpus.
    rng = np.random.default_rng(77)
    centers = rng.normal(size=(n // per_mass, d)).astype(np.float32) * 10.0
    X = (np.repeat(centers, per_mass, axis=0)
         + rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    queries = (X[rng.choice(n, size=nq, replace=False)]
               + rng.normal(size=(nq, d)) * 0.05).astype(np.float32)

    # -- recall@QPS frontier: flat vs IVF vs IVF-PQ, nprobe sweep ------------
    try:
        import jax
        import jax.numpy as jnp

        exact = exact_neighbors(queries, X, np.arange(n), k)
        qd = jnp.asarray(queries)

        def timed(fn):
            jax.block_until_ready(fn(qd))      # compile + warm
            t0 = time.perf_counter()
            for _ in range(rounds):
                out = jax.block_until_ready(fn(qd))
            return nq * rounds / (time.perf_counter() - t0), out

        Xd = jnp.asarray(X)
        x2 = jnp.sum(Xd * Xd, axis=1)

        @jax.jit
        def flat_scan(qs):
            d2 = x2[None, :] - 2.0 * qs @ Xd.T
            _, ids = jax.lax.top_k(-d2, k)
            return ids

        flat_qps, flat_ids = timed(flat_scan)
        frontier = [{
            "variant": "flat", "nprobe": None, "scan_fraction": 1.0,
            "qps": round(flat_qps, 1),
            "recall_at_10": round(
                recall_at_k(np.asarray(flat_ids), exact), 4),
        }]

        best_ivf_qps = None
        for variant, base in (
                ("ivf", IVFIndex.build(X, nlist, k=k, seed=1)),
                ("ivfpq", IVFIndex.build(X, nlist, k=k, seed=1,
                                         pq=PQConfig(m=8, ksub=16)))):
            params = {name: jnp.asarray(v)
                      for name, v in base.params.items()}
            for nprobe in (1, 2, 4, 8, 16):
                view = base.with_options(nprobe=nprobe)
                entry = lookup("retrieve", view.sig())
                static = view._static()
                run = jax.jit(lambda c, _f=entry.fn, _s=static:
                              _f(_s, params, {"query": c}))
                qps, out = timed(run)
                rec = recall_at_k(np.asarray(out[_NN_STAGE]), exact)
                scan = view.scan_fraction(queries)
                frontier.append({
                    "variant": variant, "nprobe": nprobe,
                    "scan_fraction": round(scan, 4),
                    "qps": round(qps, 1),
                    "recall_at_10": round(rec, 4),
                    "backend": entry.backend,
                })
                if variant == "ivf":
                    # the acceptance operating point: recall@10 >= 0.95
                    # while scanning <= 25% of the corpus
                    if (rec >= 0.95 and scan <= 0.25
                            and (best_ivf_qps is None
                                 or qps > best_ivf_qps)):
                        best_ivf_qps = qps
                    if nprobe == ref_nprobe:
                        results["retrieval_recall_at_10"] = round(rec, 4)
        q["frontier"] = frontier
        if best_ivf_qps is not None:
            results["retrieval_ivf_qps_ratio"] = round(
                best_ivf_qps / flat_qps, 3)
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        q["frontier_error"] = repr(exc)[:200]

    # -- p99 under multi-tenant contention -----------------------------------
    import gc
    import sys

    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)
    gc_was_enabled = gc.isenabled()
    gc.disable()
    sched = None
    try:
        idx_serve = IVFIndex.build(X, nlist, k=k, nprobe=ref_nprobe,
                                   seed=2)
        qtab = Table({"query": queries})
        sched = SharedScheduler(max_batch_rows=128, max_wait_ms=0.5,
                                queue_capacity=1 << 12)
        for i in range(n_tenants):
            sched.add_tenant(f"r{i}", idx_serve, qtab.take(2),
                             slo="interactive")
        sched.start()
        for i in range(n_tenants):            # warm every tenant's path
            sched.predict(f"r{i}", qtab.take(4), timeout=120)

        latencies: list = []
        errors: list = []
        lock = threading.Lock()

        def client(worker):
            crng = np.random.default_rng(500 + worker)
            mine = []
            try:
                for _ in range(per_client):
                    start = int(crng.integers(0, nq - 4))
                    rows = int(crng.integers(1, 5))
                    req = qtab.slice(start, start + rows)
                    t0 = time.perf_counter()
                    sched.predict(f"r{worker % n_tenants}", req,
                                  timeout=120)
                    mine.append(time.perf_counter() - t0)
                    time.sleep(0.001)
            except Exception as exc:   # noqa: BLE001
                with lock:
                    errors.append(repr(exc)[:200])
            with lock:
                latencies.extend(mine)

        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError(f"contention client lost: {errors[:3]}")
        samples = np.asarray(latencies)
        p99 = round(1e3 * float(np.quantile(samples, 0.99)), 3)
        q["contention"] = {
            "clients": n_clients,
            "requests": len(latencies),
            "req_per_s": round(len(latencies) / wall, 1),
            "p99_ms": p99,
        }
        results["retrieval_contention_p99_ms"] = p99
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        q["contention_error"] = repr(exc)[:200]
    finally:
        if sched is not None:
            sched.close()
        sys.setswitchinterval(old_switch)
        if gc_was_enabled:
            gc.enable()
            gc.collect()

    # -- index-publish latency: insert deltas vs full republishes ------------
    try:
        from flink_ml_tpu.online import DeltaEncoder
        from flink_ml_tpu.serving import serve_model

        reps = 5 if smoke else 20
        batch_rows = 8
        # slack covers every planned insert even if one list takes them
        # all, so no delta overflows a block and re-anchors mid-run —
        # the leg times shape-stable generation swaps, not redeploys
        idx_pub = IVFIndex.build(X[:n // 2], nlist, k=k, seed=3,
                                 drift_threshold=None,
                                 list_slack=8 + reps * batch_rows)
        endpoint = serve_model(idx_pub,
                               Table({"query": queries}).take(2),
                               max_batch_rows=64, max_wait_ms=0.5)
        try:
            pub = endpoint.delta_publisher()
            enc = DeltaEncoder()
            pub.apply(enc.encode(1, idx_pub.params, pub.stats))
            enc.ack()                         # anchor generation
            cur, step = idx_pub, 2
            delta_s, full_s, payloads = [], [], []
            for _ in range(reps):
                _, nxt = cur.updated(inserts=rng.normal(
                    size=(batch_rows, d)).astype(np.float32))
                t0 = time.perf_counter()      # the publish, not the
                update = enc.encode(step, nxt.params, pub.stats)
                pub.apply(update)
                enc.ack()                     # host-side index edit
                delta_s.append(time.perf_counter() - t0)
                pb = getattr(update, "payload_bytes", None)
                if pb is not None:
                    payloads.append(pb)
                cur, step = nxt, step + 1
            for _ in range(reps):
                fenc = DeltaEncoder()         # fresh encoder: anchors
                t0 = time.perf_counter()      # as a FULL republish
                pub.apply(fenc.encode(1, cur.params, pub.stats))
                fenc.ack()
                full_s.append(time.perf_counter() - t0)
            dm = float(np.median(delta_s))
            fm = float(np.median(full_s))
            full_bytes = sum(int(a.size) * int(a.itemsize)
                             for a in cur.params.values())
            q["publish"] = {
                "reps": reps,
                "rows_per_delta": batch_rows,
                "delta_ms": round(1e3 * dm, 3),
                "full_ms": round(1e3 * fm, 3),
                # the codec's serving win is bytes shipped to replicas,
                # not in-process CPU: a dense-tree diff still walks the
                # whole tree, so a tiny delta can cost MORE wall time
                # than a full swap at smoke index sizes (ratio > 1)
                "delta_payload_bytes": (int(np.median(payloads))
                                        if payloads else None),
                "full_bytes": full_bytes,
            }
            results["retrieval_publish_delta_vs_full_ratio"] = round(
                dm / fm, 3)
        finally:
            endpoint.close()
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        q["publish_error"] = repr(exc)[:200]


def bench_failover(results: dict) -> None:
    """Serving fleet failover leg (failover_metric_version 1, ISSUE 20):
    kill one chip of a 4-chip fleet at a dispatch boundary under a live
    closed-loop client sweep, twice — once with the victim tenant
    placed on a single chip (full move + re-admission) and once 2-way
    replicated (a survivor keeps serving; the failover window is one
    dispatch, no re-warm).

    - **Recovery wall**: the FailoverReport's detection -> recovered
      span (requeue + CAS re-placement on the shared generation stream
      + re-admission), per variant.
    - **Interactive p99 before/during/after** the kill — the brownout
      ladder sheds bulk at admission while the fleet is short, so the
      protected class's tail should move little across the fault.
    - **Drops**: every client request across the kill must be answered
      — ``failover_dropped_requests`` MUST be 0 (the requeue keeps
      futures intact; retried answers are bit-identical, asserted in
      tests/test_faults.py).
    - **Replication A/B**: replicated recovery wall / unreplicated —
      what the params-only HBM copy buys.

    Measured fields are null, never faked, when a sub-leg fails."""
    import threading

    from flink_ml_tpu import Table
    from flink_ml_tpu.autoscale.placement import PlacementStore
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)
    from flink_ml_tpu.robustness import FaultPlan
    from flink_ml_tpu.serving import (DISPATCH_SCOPE, FailoverDriver,
                                      ServingOverloadedError,
                                      SharedScheduler)

    smoke = _smoke()
    n_clients = 16 if smoke else 64
    per_phase = 25 if smoke else 100
    d = 32

    fo: dict = {
        "failover_metric_version": 1,
        "config": f"LR d={d}, victim tenant + 1 bulk tenant on a 4-chip "
                  f"placement, {n_clients} closed-loop interactive "
                  f"clients x {per_phase} reqs per phase "
                  "(before/during/after), chip_down injected at a "
                  "dispatch boundary early in 'during'; A/B: victim "
                  "solo-placed vs 2-way replicated",
        "unreplicated": None,
        "replicated": None,
        "p99_before_ms": None,
        "p99_during_ms": None,
        "p99_after_ms": None,
    }
    results["notes"]["failover"] = fo
    # headline fields: pre-nulled at leg entry, never faked
    results.setdefault("failover_recovery_s", None)
    results.setdefault("failover_dropped_requests", None)
    results.setdefault("failover_replicated_recovery_ratio", None)

    rng = np.random.default_rng(23)
    model = LogisticRegressionModel()
    model.set_model_data(Table({
        "coefficients": rng.normal(size=(1, d)),
        "intercept": np.array([0.1])}))
    feats = Table({"features": rng.normal(size=(1024, d))
                   .astype(np.float32)})

    def run_variant(replicas):
        """One full kill-and-recover pass; returns the variant record
        (recovery wall, phase p99s, drops, failover audit fields)."""
        sched = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                                queue_capacity=1 << 13)
        try:
            sched.add_tenant("inter", model, feats.take(2),
                             slo="interactive")
            sched.add_tenant("bulk0", model, feats.take(2), slo="bulk")
            store = PlacementStore(4)
            # victim tenant on chip 3 — the newest lease, the
            # deterministic LIFO victim of the injected death
            store.publish({"inter": [3], "bulk0": [0]}, 0)
            driver = FailoverDriver(sched, store, chips=[0, 1, 2, 3])
            if replicas > 1:
                driver.ensure_replicas("inter", replicas)
            sched.start()

            drops: list = []
            bulk_sheds = [0]

            def sweep(samples):
                lock = threading.Lock()

                def client(worker):
                    crng = np.random.default_rng(300 + worker)
                    mine = []
                    try:
                        for i in range(per_phase):
                            start = int(crng.integers(0, 1000))
                            rows = int(crng.integers(1, 5))
                            req = feats.slice(start, start + rows)
                            t0 = time.perf_counter()
                            sched.predict("inter", req, timeout=120)
                            mine.append(time.perf_counter() - t0)
                            if i % 4 == 0:
                                # background bulk traffic: sheds are
                                # EXPECTED once the brownout raises —
                                # that is the ladder working, not a drop
                                try:
                                    sched.submit(
                                        "bulk0", feats.take(8))
                                except ServingOverloadedError:
                                    with lock:
                                        bulk_sheds[0] += 1
                            time.sleep(0.001)
                    except Exception as exc:   # noqa: BLE001
                        with lock:
                            drops.append(repr(exc)[:200])
                    with lock:
                        samples.extend(mine)

                threads = [threading.Thread(target=client, args=(w,))
                           for w in range(n_clients)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(300)

            def p99_ms(samples):
                return (round(1e3 * float(np.quantile(
                    np.asarray(samples), 0.99)), 3)
                    if samples else None)

            warm: list = []
            sweep(warm)                       # every path compiled+warm
            before: list = []
            sweep(before)
            during: list = []
            plan = FaultPlan(seed=20).inject(DISPATCH_SCOPE, at=5,
                                             kind="chip_down")
            with plan:
                sweep(during)
            after: list = []
            sweep(after)

            if len(driver.reports) != 1:
                raise RuntimeError(
                    f"expected exactly one failover, saw "
                    f"{len(driver.reports)} (fires={plan.fires})")
            rep = driver.reports[0]
            return {
                "recovery_s": round(rep.wall_s, 6),
                "requeued": rep.requeued,
                "moved": list(rep.moved),
                "kept_replica": list(rep.replicated),
                "conflicts": rep.conflicts,
                "placement_generation": rep.generation,
                "brownout_level": driver.brownout_level,
                "bulk_sheds": bulk_sheds[0],
                "drops": len(drops),
                "deadline_sheds": sched._deadline_shed.value,
                "p99_before_ms": p99_ms(before),
                "p99_during_ms": p99_ms(during),
                "p99_after_ms": p99_ms(after),
            }
        finally:
            sched.close()

    total_drops = None
    try:
        solo = run_variant(replicas=1)
        fo["unreplicated"] = solo
        fo["p99_before_ms"] = solo["p99_before_ms"]
        fo["p99_during_ms"] = solo["p99_during_ms"]
        fo["p99_after_ms"] = solo["p99_after_ms"]
        results["failover_recovery_s"] = solo["recovery_s"]
        total_drops = solo["drops"]
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        fo["unreplicated_error"] = repr(exc)[:200]
    try:
        repl = run_variant(replicas=2)
        fo["replicated"] = repl
        if total_drops is not None:
            total_drops += repl["drops"]
        if fo["unreplicated"] is not None \
                and solo["recovery_s"] > 0:
            results["failover_replicated_recovery_ratio"] = round(
                repl["recovery_s"] / solo["recovery_s"], 3)
    except Exception as exc:   # noqa: BLE001 — nulled, never faked
        fo["replicated_error"] = repr(exc)[:200]
    results["failover_dropped_requests"] = total_drops


def main() -> None:
    tpu_ok = _probe_tpu_backend()
    if not tpu_ok:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    results: dict = {"notes": {}}
    # nproc on record every round: single-core hosts cannot demonstrate
    # parallel-ingest scaling (INGEST_SCALING.md) — make that legible
    results["notes"]["host_nproc"] = os.cpu_count() or 1
    if not tpu_ok:
        results["notes"]["tpu_unavailable"] = (
            "axon backend probe failed/timed out; this line is the CPU "
            "smoke pass, NOT a TPU measurement")
    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    # the headline leg must succeed on a healthy backend; if the relay
    # dies BETWEEN the probe and the timing (r4's failure mode was
    # before the probe, but a mid-run drop would otherwise produce zero
    # output), emit a parseable line with the error instead of nothing
    try:
        bench_logreg(results)
    except Exception as exc:   # noqa: BLE001
        results["notes"]["bench_logreg_error"] = repr(exc)[:300]
        results.setdefault("logreg_epochs_per_sec", 0.0)
        results.setdefault("vs_baseline", 0.0)
        results["notes"].setdefault(
            "tpu_unavailable",
            "headline leg failed mid-run (backend died after the "
            "probe?) — this line records the failure, not a rate")
    for leg in (bench_logreg_outofcore, bench_criteo_e2e, bench_kmeans,
                bench_workset, bench_widedeep, bench_als, bench_gbt,
                bench_online_ftrl, bench_serving, bench_pipeline,
                bench_comm, bench_wal, bench_recovery, bench_online,
                bench_kernels, bench_coldstart, bench_obs,
                bench_multitenant, bench_int8, bench_retrieval,
                bench_failover, bench_elastic, bench_autoscale):
        try:
            leg(results)
        except Exception as exc:   # noqa: BLE001
            results["notes"][f"{leg.__name__}_error"] = repr(exc)[:300]
    if profile_dir:
        jax.profiler.stop_trace()
        results["notes"]["profile_dir"] = profile_dir

    line = {
        "metric": "logreg_epochs_per_sec",
        "value": results.pop("logreg_epochs_per_sec"),
        "unit": "epochs/s",
        "vs_baseline": results.pop("vs_baseline"),
    }
    line.update(results)
    print(json.dumps(line))
    # final self-sufficient summary line (VERDICT r4 weak #5): the
    # driver's capture truncates long output to a 4 KB TAIL, which cut
    # the headline `value` out of BENCH_r04.json — so the LAST stdout
    # line always carries the verdict-critical fields on its own, and is
    # itself a valid bench line if a parser takes the last line instead
    # of the first.
    print(json.dumps({
        "metric": line["metric"], "value": line["value"],
        "unit": line["unit"], "vs_baseline": line["vs_baseline"],
        "summary": True,
        "backend": jax.default_backend(),
        "lr_impl": line.get("notes", {}).get("lr_impl"),
        "tpu_unavailable": bool(
            line.get("notes", {}).get("tpu_unavailable")),
    }))


if __name__ == "__main__":
    main()
