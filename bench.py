"""Headline benchmark: KMeans iterations/sec on TPU (BASELINE.md target).

Prints ONE JSON line:
    {"metric": "kmeans_iterations_per_sec", "value": N, "unit": "iter/s",
     "vs_baseline": R}

The reference publishes no numbers (BASELINE.md), so the baseline is the
driver-specified host-loop anchor: the same Lloyd's iteration in numpy on
the host CPU (measured on a subsample and scaled linearly — the kernel is
exactly O(n) in points).  vs_baseline = tpu_rate / host_rate.

The benchmarked step is exactly what ``KMeans.fit`` plans for this shape on
a TPU backend: the fused Pallas stats kernel (``ops/kmeans_pallas.py``,
tie_policy="fast", f32, block_n=8192) — ~3.5x the XLA expansion of the same
iteration, which HBM-round-trips two (n, k) intermediates per step.

Timing methodology (axon-tunnel gotchas, measured empirically):
- block_until_ready does not actually block through the tunnel; np.asarray
  (device_get) is the only reliable completion fence.
- every run call pays a fixed ~70 ms tunnel round-trip, so short scans
  understate the device rate badly (30-iter scans measure ~190 "iter/s" for
  a 300 iter/s program); ITERS=480 keeps the bias under ~15%.
- repeated calls with identical args can be served from a relay-side cache;
  every timed trial uses a distinct init.
"""

import json
import time

import numpy as np

# Problem size: 1M points, 64 dims, 256 clusters -> ~34 GFLOP per iteration,
# comfortably MXU-bound on one v5e chip.
N, D, K = 1_048_576, 64, 256
ITERS = 480
HOST_SUBSAMPLE = 16  # numpy baseline runs N/16 points and scales


def _host_baseline_rate(points: np.ndarray, centroids: np.ndarray) -> float:
    """Host numpy Lloyd's iteration rate (iterations/sec), subsampled."""
    sub = points[: N // HOST_SUBSAMPLE]
    reps = 2
    start = time.perf_counter()
    c = centroids.copy()
    for _ in range(reps):
        # ||x||^2 - 2 x.c + ||c||^2 argmin, then segment mean
        cross = sub @ c.T
        d2 = (sub * sub).sum(1)[:, None] - 2 * cross + (c * c).sum(1)[None, :]
        assign = d2.argmin(1)
        sums = np.zeros_like(c)
        np.add.at(sums, assign, sub)
        counts = np.bincount(assign, minlength=K).astype(np.float32)
        nonzero = counts > 0
        c[nonzero] = sums[nonzero] / counts[nonzero, None]
    elapsed = time.perf_counter() - start
    per_full_iter = (elapsed / reps) * HOST_SUBSAMPLE
    return 1.0 / per_full_iter


def main() -> None:
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering import kmeans as km

    rng = np.random.default_rng(0)
    points_host = rng.normal(size=(N, D)).astype(np.float32)
    init_host = points_host[rng.permutation(N)[:K]]

    measure = DistanceMeasure.get_instance("euclidean")
    mesh = km.default_mesh()
    impl, block_n = km._plan_fit_impl(N, D, K, measure, mesh)
    if impl == "pallas":
        body = km.kmeans_epoch_step_pallas(K, block_n=block_n)
    else:  # non-TPU backend fallback: the XLA body
        body = km.kmeans_epoch_step(measure, K)

    points = jnp.asarray(points_host)
    mask = jnp.ones((N,), jnp.float32)
    init = jnp.asarray(init_host)

    # One jitted program reused across calls so the timed runs are compile-
    # cache hits (the fused `iterate` path builds the identical lax.scan
    # program).
    @jax.jit
    def run_iters(centroids, points, mask):
        def scan_step(c, epoch):
            return body(c, epoch, (points, mask)).feedback, None

        final, _ = jax.lax.scan(scan_step, centroids,
                                jnp.arange(ITERS, dtype=jnp.int32))
        return final

    np.asarray(run_iters(init, points, mask))  # compile + warmup
    trials = []
    for trial in range(1, 4):
        trial_init = points[K * trial:K * (trial + 1)] + 0.0
        start = time.perf_counter()
        np.asarray(run_iters(trial_init, points, mask))
        trials.append(time.perf_counter() - start)
    tpu_rate = ITERS / min(trials)

    host_rate = _host_baseline_rate(points_host, init_host)

    print(json.dumps({
        "metric": "kmeans_iterations_per_sec",
        "value": round(tpu_rate, 3),
        "unit": "iter/s",
        "vs_baseline": round(tpu_rate / host_rate, 3),
    }))


if __name__ == "__main__":
    main()
